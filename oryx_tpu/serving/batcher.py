"""Request-coalescing micro-batcher for device top-k scoring.

The reference serves each /recommend request by fanning one thread pool
over LSH partitions (ALSServingModel.java:264-279; LoadBenchmark.java
measures ~1-2 concurrent requests saturating a 32-core host). On TPU the
equivalent hot loop is a single [B,K]x[K,I] matmul + top_k — but one
device dispatch per HTTP request wastes the MXU (B=1) and, worse, a
data-dependent k (how_many + len(exclude)) makes every distinct request
shape a fresh XLA compile.

This batcher fixes both:

- Concurrent requests are coalesced into ONE topk_dot_batch dispatch.
  Coalescing is *natural backpressure*, not a timer: while the dispatcher
  thread is busy scoring batch N, new arrivals queue up and become batch
  N+1. An idle server dispatches a single request immediately — no added
  latency floor.
- Shapes are bucketed: the row count pads up to a power of two (zero
  rows) and k rounds up to a fixed bucket, then results are trimmed
  host-side — so the jit cache holds a few dozen entries total instead of
  one per distinct (concurrency, exclusion-set-size) pair.

One process-wide dispatcher is shared across model swaps (serving managers
replace their model object on every MODEL update); requests are grouped by
the identity of the device matrix they score against, so a swap mid-window
simply splits one dispatch into two.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import Future

import numpy as np

log = logging.getLogger(__name__)

from oryx_tpu.ops.als import PALLAS_TOPK_MAX_K

# k rounds up to the smallest of these (then min'd with the item count);
# larger requests fall back to next_pow2(k). A few buckets cover every
# realistic how_many + exclusion overfetch without recompiles. The
# PALLAS_TOPK_MAX_K bucket matters: a default /recommend?howMany=10
# overfetches to k=18, and this bucket keeps it on the fused Pallas path
# instead of jumping to the 128 bucket's XLA fallback.
K_BUCKETS = (16, PALLAS_TOPK_MAX_K, 128, 1024)

MAX_BATCH = 4096  # rows per device dispatch (the bench-measured knee)


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def k_bucket(k: int) -> int:
    for b in K_BUCKETS:
        if k <= b:
            return b
    return _next_pow2(k)


class _Pending:
    __slots__ = ("vec", "k", "y", "future")

    def __init__(self, vec, k, y, future):
        self.vec = vec
        self.k = k
        self.y = y
        self.future = future


class TopKBatcher:
    """Coalesces top-k scoring requests into batched device dispatches."""

    _shared: "TopKBatcher | None" = None
    _shared_lock = threading.Lock()

    @classmethod
    def shared(cls) -> "TopKBatcher":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = TopKBatcher()
        return cls._shared

    def __init__(self, max_batch: int = MAX_BATCH):
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: list[_Pending] = []
        self._thread: threading.Thread | None = None
        self._closed = False
        # observability: dispatch count + coalesced-request count let a
        # /metrics scrape compute the achieved mean batch size
        self.dispatches = 0
        self.coalesced = 0

    # -- public API --------------------------------------------------------

    def submit(self, vec: np.ndarray, k: int, y) -> tuple[np.ndarray, np.ndarray]:
        """Score vec against device matrix y, returning (values, indices)
        for the top-k rows. Blocks until the coalesced dispatch completes.
        """
        fut: Future = Future()
        p = _Pending(np.asarray(vec, dtype=np.float32), int(k), y, fut)
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._ensure_thread()
            self._queue.append(p)
            self._cond.notify()
        return fut.result()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- dispatcher --------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="oryx-topk-batcher", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        # Depth-1 pipeline: launch batch N+1's device work (with async
        # device->host copies) BEFORE materializing batch N's results. A
        # blocking fetch without a prior copy_to_host_async costs a full
        # synchronous transport round trip — measured 2600 ms (!) for a
        # B=1 dispatch on the tunneled TPU vs 38 ms pipelined — so the
        # overlap is not an optimization, it is the difference between a
        # usable and an unusable serving tier on remote-attached devices.
        inflight: list[tuple[list[_Pending], int, object, object]] = []
        while True:
            with self._cond:
                while not self._queue and not self._closed and not inflight:
                    self._cond.wait()
                if self._closed and not self._queue and not inflight:
                    return
                batch, self._queue = self._queue[: self.max_batch], self._queue[self.max_batch:]
            try:
                launched = self._launch(batch) if batch else []
            except Exception as e:  # pragma: no cover - defensive: a failure
                # before the per-group guard (grouping, imports) must fail
                # the whole batch, not kill the thread with futures pending
                log.exception("batcher launch failed")
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(e)
                launched = []
            for item in inflight:
                self._resolve(item)
            inflight = launched

    def _launch(
        self, batch: list[_Pending]
    ) -> list[tuple[list[_Pending], int, object, object]]:
        """Issue one device dispatch per (matrix, k-bucket) group and start
        the async result copies; returns the in-flight group handles."""
        import jax.numpy as jnp

        from oryx_tpu.ops.als import topk_dot_batch

        groups: dict[tuple[int, int], list[_Pending]] = {}
        for p in batch:
            n = p.y.shape[0]
            kb = min(k_bucket(p.k), n)
            groups.setdefault((id(p.y), kb), []).append(p)

        self.dispatches += len(groups)
        self.coalesced += len(batch)

        launched = []
        for (_, kb), group in groups.items():
            # failures stay inside their group: a bad shape / OOM against
            # one target matrix must not fail requests scoring another
            try:
                y = group[0].y
                b = len(group)
                padded = _next_pow2(b)
                xs = np.zeros((padded, y.shape[1]), dtype=np.float32)
                for i, p in enumerate(group):
                    xs[i] = p.vec
                vals, idx = topk_dot_batch(jnp.asarray(xs), y, k=kb)
                try:
                    vals.copy_to_host_async()
                    idx.copy_to_host_async()
                except AttributeError:  # non-jax array (tests with stubs)
                    pass
                launched.append((group, kb, vals, idx))
            except Exception as e:
                log.exception("batcher group dispatch failed (k=%d)", kb)
                for p in group:
                    if not p.future.done():
                        p.future.set_exception(e)
        return launched

    def _resolve(self, item: tuple[list[_Pending], int, object, object]) -> None:
        group, kb, vals_dev, idx_dev = item
        try:
            vals = np.asarray(vals_dev)
            idx = np.asarray(idx_dev)
            for i, p in enumerate(group):
                k_eff = min(p.k, kb)
                p.future.set_result((vals[i, :k_eff], idx[i, :k_eff]))
        except Exception as e:
            log.exception("batcher group resolve failed (k=%d)", kb)
            for p in group:
                if not p.future.done():
                    p.future.set_exception(e)
