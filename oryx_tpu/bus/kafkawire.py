"""Kafka wire-protocol codec: primitive types + record-batch v2.

Self-contained (zero dependencies) encoding/decoding for the subset of the
Kafka protocol the framework's bus needs, pinned to pre-"flexible-version"
API versions so all types are the classic fixed-width/length-prefixed forms.
Used by the kafka:// Broker backend (oryx_tpu/bus/kafka.py) and by the
in-process protocol test server (tests/kafka_testbroker.py) — the analogue
of the reference booting a real LocalKafkaBroker inside the JVM for its
integration tests (framework/kafka-util src/test .../LocalKafkaBroker.java).

Record batches are magic-v2 (the only format modern brokers accept for
produce): varint/zigzag record fields, CRC32C over attributes..end.
Compression is not emitted; inbound batches are decoded for every
codec real producers use — gzip and snappy (raw or xerial-framed) in
pure python, lz4-frame and zstd through the host's canonical C
libraries (bus/compress.py ctypes bindings).
"""

from __future__ import annotations

import struct
import zlib


class WireDecodeError(ValueError):
    """A record batch that claims to be complete (its length prefix is
    fully present) decodes to garbage — truncated mid-frame, bit-flipped,
    or otherwise internally inconsistent. Distinct from the tolerated
    *trailing partial* batch a broker may legitimately return at the end
    of a fetch: that one is silently re-fetched, this one must fail the
    consume loudly with context, because retrying the same bytes can
    never succeed and guessing at record boundaries would desync every
    later offset in the stream."""


# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — Kafka record-batch checksum. Table-driven, reflected
# polynomial 0x82F63B78. Check value: crc32c(b"123456789") == 0xE3069283.
# ---------------------------------------------------------------------------

def _make_crc32c_tables() -> list[list[int]]:
    """Slicing-by-8 tables: t[0] is the classic byte table; t[k][b] is the
    CRC of byte b followed by k zero bytes. 8 bytes per loop step keeps a
    16 MB MODEL publish in the tens-of-ms range instead of seconds."""
    t0 = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        t0.append(c)
    tables = [t0]
    for k in range(1, 8):
        prev = tables[k - 1]
        tables.append([t0[prev[n] & 0xFF] ^ (prev[n] >> 8) for n in range(256)])
    return tables


_T = _make_crc32c_tables()

def _crc32c_py(data: bytes, crc: int = 0) -> int:
        t0, t1, t2, t3, t4, t5, t6, t7 = _T
        crc ^= 0xFFFFFFFF
        mv = memoryview(data)
        n = len(mv)
        i = 0
        end8 = n - (n % 8)
        while i < end8:
            b0, b1, b2, b3, b4, b5, b6, b7 = mv[i : i + 8]
            crc ^= b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)
            crc = (
                t7[crc & 0xFF]
                ^ t6[(crc >> 8) & 0xFF]
                ^ t5[(crc >> 16) & 0xFF]
                ^ t4[(crc >> 24) & 0xFF]
                ^ t3[b4]
                ^ t2[b5]
                ^ t1[b6]
                ^ t0[b7]
            )
            i += 8
        while i < n:
            crc = t0[(crc ^ mv[i]) & 0xFF] ^ (crc >> 8)
            i += 1
        return crc ^ 0xFFFFFFFF


def _resolve_crc32c():
    """Fastest available implementation: google_crc32c (C extension) >
    the project's native library (SSE4.2 hardware CRC32, measured 673x
    the Python fallback on a 16MB MODEL publish) > pure python.

    Called lazily on the first crc32c() use, NOT at import: the native
    tier may auto-BUILD liboryxbus.so (a g++ subprocess), and importing
    this module must never block on a compiler."""
    try:
        import google_crc32c as _gcrc  # type: ignore

        def crc32c_ext(data: bytes, crc: int = 0) -> int:
            return _gcrc.extend(crc, bytes(data))

        return crc32c_ext
    except ImportError:
        pass
    try:
        import ctypes

        from oryx_tpu.bus.native import _find_lib

        path = _find_lib()
        if path:
            lib = ctypes.CDLL(path)
            fn = getattr(lib, "oryxbus_crc32c", None)  # stale .so: absent
            if fn is not None:
                fn.restype = ctypes.c_uint32
                fn.argtypes = [
                    ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
                ]
                if fn(b"123456789", 9, 0) == 0xE3069283:  # self-check

                    def crc32c_native(data: bytes, crc: int = 0) -> int:
                        return fn(bytes(data), len(data), crc)

                    return crc32c_native
    except Exception:  # noqa: BLE001 - any native problem -> python path
        pass
    return _crc32c_py


_crc32c_impl = None


def crc32c(data: bytes, crc: int = 0) -> int:
    """Lazy dispatch: the first call resolves and caches the fastest
    implementation (callers commonly hold `from ... import crc32c`
    bindings, so the cache lives in a module var, not by rebinding
    this name)."""
    global _crc32c_impl
    if _crc32c_impl is None:
        _crc32c_impl = _resolve_crc32c()
    return _crc32c_impl(data, crc)


# ---------------------------------------------------------------------------
# primitive writers / readers
# ---------------------------------------------------------------------------

class Writer:
    def __init__(self):
        self._parts: list[bytes] = []

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(b)
        return self

    def i8(self, v: int) -> "Writer":
        return self.raw(struct.pack(">b", v))

    def i16(self, v: int) -> "Writer":
        return self.raw(struct.pack(">h", v))

    def i32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">i", v))

    def i64(self, v: int) -> "Writer":
        return self.raw(struct.pack(">q", v))

    def u32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">I", v))

    def string(self, s: str | None) -> "Writer":
        if s is None:
            return self.i16(-1)
        b = s.encode("utf-8")
        return self.i16(len(b)).raw(b)

    def bytes_(self, b: bytes | None) -> "Writer":
        if b is None:
            return self.i32(-1)
        return self.i32(len(b)).raw(b)

    def array(self, items, write_one) -> "Writer":
        if items is None:
            return self.i32(-1)
        self.i32(len(items))
        for it in items:
            write_one(self, it)
        return self

    def varint(self, v: int) -> "Writer":
        """Zigzag varint (signed)."""
        z = (v << 1) ^ (v >> 63) if v < 0 else v << 1
        while True:
            b = z & 0x7F
            z >>= 7
            if z:
                self.raw(bytes([b | 0x80]))
            else:
                self.raw(bytes([b]))
                return self

    def done(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def remaining(self) -> int:
        return len(self.data) - self.pos

    def raw(self, n: int) -> bytes:
        b = self.data[self.pos : self.pos + n]
        if len(b) < n:
            raise EOFError(f"need {n} bytes, have {len(b)}")
        self.pos += n
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self.raw(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.raw(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.raw(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.raw(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.raw(4))[0]

    def string(self) -> str | None:
        n = self.i16()
        if n < 0:
            return None
        return self.raw(n).decode("utf-8")

    def bytes_(self) -> bytes | None:
        n = self.i32()
        if n < 0:
            return None
        return self.raw(n)

    def array(self, read_one) -> list | None:
        n = self.i32()
        if n < 0:
            return None
        return [read_one(self) for _ in range(n)]

    def varint(self) -> int:
        shift = 0
        z = 0
        while True:
            b = self.raw(1)[0]
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 63:
                # corrupt data can present an endless continuation-bit
                # run; a real zigzag-64 never needs more than 10 bytes
                raise WireDecodeError("varint exceeds 64 bits")
        return (z >> 1) ^ -(z & 1)  # un-zigzag


# ---------------------------------------------------------------------------
# snappy (RFC-less: google/snappy format description + the xerial stream
# framing the Java Kafka client wraps it in) — pure-python DECODER so
# compressed batches from foreign JVM/librdkafka producers are readable
# without a native dependency. Compression on our own produce path stays
# off (uncompressed batches; the broker accepts either).
# ---------------------------------------------------------------------------

_XERIAL_MAGIC = b"\x82SNAPPY\x00"


def _snappy_block_decompress(data: bytes) -> bytes:
    """One raw snappy block: uvarint uncompressed length, then
    literal/copy tagged elements."""
    ulen = 0
    shift = 0
    pos = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated snappy preamble")
        b = data[pos]
        pos += 1
        ulen |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        ttype = tag & 3
        if ttype == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                nb = ln - 59
                ln = int.from_bytes(data[pos:pos + nb], "little")
                pos += nb
            ln += 1
            out += data[pos:pos + ln]
            pos += ln
            continue
        if ttype == 1:  # copy, 1-byte offset
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif ttype == 2:  # copy, 2-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise ValueError("bad snappy copy offset")
        if off >= ln:  # non-overlapping: one slice
            out += out[len(out) - off:len(out) - off + ln]
        else:  # overlapping run: byte-wise (RLE-style copies)
            for _ in range(ln):
                out.append(out[-off])
    if len(out) != ulen:
        raise ValueError(f"snappy length mismatch: {len(out)} != {ulen}")
    return bytes(out)


def snappy_decompress(data: bytes) -> bytes:
    """Raw snappy block, or the xerial-framed stream
    (magic + 2 version ints, then [i32 length][block] chunks) that the
    Java Kafka client's SnappyOutputStream writes."""
    if data[: len(_XERIAL_MAGIC)] == _XERIAL_MAGIC:
        pos = len(_XERIAL_MAGIC) + 8  # version + compat ints
        out = bytearray()
        while pos + 4 <= len(data):
            (n,) = struct.unpack_from(">i", data, pos)
            pos += 4
            out += _snappy_block_decompress(data[pos:pos + n])
            pos += n
        return bytes(out)
    return _snappy_block_decompress(data)


# ---------------------------------------------------------------------------
# record batch v2
# ---------------------------------------------------------------------------

def encode_record_batch(
    records: list[tuple[bytes | None, bytes | None]],
    base_timestamp_ms: int,
) -> bytes:
    """[(key, value), ...] -> one magic-v2 record batch (uncompressed)."""
    body = Writer()
    for i, (key, value) in enumerate(records):
        rec = Writer()
        rec.i8(0)  # record attributes
        rec.varint(0)  # timestamp delta
        rec.varint(i)  # offset delta
        if key is None:
            rec.varint(-1)
        else:
            rec.varint(len(key)).raw(key)
        if value is None:
            rec.varint(-1)
        else:
            rec.varint(len(value)).raw(value)
        rec.varint(0)  # headers count
        rb = rec.done()
        body.varint(len(rb)).raw(rb)
    records_bytes = body.done()

    # fields covered by the CRC: attributes .. records
    crced = (
        Writer()
        .i16(0)  # attributes: no compression, create-time timestamps
        .i32(len(records) - 1)  # lastOffsetDelta
        .i64(base_timestamp_ms)
        .i64(base_timestamp_ms)  # maxTimestamp
        .i64(-1)  # producerId
        .i16(-1)  # producerEpoch
        .i32(-1)  # baseSequence
        .i32(len(records))
        .raw(records_bytes)
        .done()
    )
    crc = crc32c(crced)
    after_length = (
        Writer().i32(-1).i8(2).u32(crc).raw(crced).done()  # leaderEpoch, magic, crc
    )
    return Writer().i64(0).i32(len(after_length)).raw(after_length).done()


def decode_record_batches(
    data: bytes,
) -> list[tuple[int, bytes | None, bytes | None]]:
    """Concatenated record batches -> [(absolute offset, key, value), ...].

    Tolerates a trailing partial batch (brokers may return one at the end
    of a fetch response). Handles magic v2; gzip/snappy (pure python)
    and lz4/zstd (system-library ctypes bindings, bus/compress.py)
    compressed v2 batches are decompressed.
    """
    out: list[tuple[int, bytes | None, bytes | None]] = []
    r = Reader(data)
    while r.remaining() >= 12:
        base_offset = r.i64()
        batch_len = r.i32()
        if batch_len < 0 or r.remaining() < batch_len:
            break  # partial trailing batch (re-fetched from the same offset)
        batch = Reader(r.raw(batch_len))
        try:
            out.extend(_decode_one_batch(batch, base_offset))
        except WireDecodeError:
            raise
        # OSError/zlib.error cover corrupt COMPRESSED payloads
        # (gzip.BadGzipFile is an OSError; mid-stream gzip corruption is
        # zlib.error): no real I/O happens in the decode, so OSError here
        # can only mean bad bytes — and it must not escape as a
        # "transient" error the consume retry would pointlessly replay
        except (
            EOFError, ValueError, struct.error, MemoryError, OSError,
            zlib.error,
        ) as e:
            # the length prefix promised a complete batch but the bytes
            # inside don't parse: a mid-frame cut or corruption. Fail THIS
            # consume with the offset context — never guess at boundaries
            # and keep scanning, which would desync every later offset.
            raise WireDecodeError(
                f"corrupt record batch at base offset {base_offset} "
                f"(len {batch_len}): {type(e).__name__}: {e}"
            ) from e
    return out


def _decode_one_batch(
    batch: Reader, base_offset: int
) -> list[tuple[int, bytes | None, bytes | None]]:
    """Decode one complete-length record batch body (v2)."""
    batch.i32()  # partitionLeaderEpoch
    magic = batch.i8()
    if magic != 2:
        raise ValueError(f"unsupported record batch magic {magic}")
    batch.u32()  # crc (not re-verified on read)
    attributes = batch.i16()
    batch.i32()  # lastOffsetDelta
    batch.i64()  # baseTimestamp
    batch.i64()  # maxTimestamp
    batch.i64()  # producerId
    batch.i16()  # producerEpoch
    batch.i32()  # baseSequence
    n_records = batch.i32()
    payload = batch.raw(batch.remaining())
    codec = attributes & 0x07
    if codec == 1:  # gzip
        import gzip as _gzip

        payload = _gzip.decompress(payload)
    elif codec == 2:  # snappy (raw or xerial-framed)
        payload = snappy_decompress(payload)
    elif codec == 3:  # lz4 frame
        from oryx_tpu.bus.compress import lz4f_decompress

        payload = lz4f_decompress(payload)
    elif codec == 4:  # zstd
        from oryx_tpu.bus.compress import zstd_decompress

        payload = zstd_decompress(payload)
    elif codec != 0:
        raise ValueError(f"unsupported compression codec {codec}")
    out: list[tuple[int, bytes | None, bytes | None]] = []
    pr = Reader(payload)
    for _ in range(n_records):
        length = pr.varint()
        if length < 0 or length > pr.remaining():
            raise ValueError(
                f"record length {length} exceeds remaining payload "
                f"{pr.remaining()}"
            )
        rec = Reader(pr.raw(length))
        rec.i8()  # attributes
        rec.varint()  # timestampDelta
        offset_delta = rec.varint()
        klen = rec.varint()
        key = rec.raw(klen) if klen >= 0 else None
        vlen = rec.varint()
        value = rec.raw(vlen) if vlen >= 0 else None
        n_headers = rec.varint()
        for _ in range(n_headers):
            hklen = rec.varint()
            rec.raw(max(0, hklen))
            hvlen = rec.varint()
            if hvlen > 0:
                rec.raw(hvlen)
        out.append((base_offset + offset_delta, key, value))
    return out


# ---------------------------------------------------------------------------
# api keys / error codes
# ---------------------------------------------------------------------------

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_API_VERSIONS = 18
API_CREATE_TOPICS = 19
API_DELETE_TOPICS = 20

ERR_NONE = 0
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3
ERR_NOT_LEADER = 6
ERR_TOPIC_ALREADY_EXISTS = 36

ERROR_NAMES = {
    0: "NONE",
    1: "OFFSET_OUT_OF_RANGE",
    3: "UNKNOWN_TOPIC_OR_PARTITION",
    5: "LEADER_NOT_AVAILABLE",
    6: "NOT_LEADER_FOR_PARTITION",
    7: "REQUEST_TIMED_OUT",
    36: "TOPIC_ALREADY_EXISTS",
}


def encode_request(
    api_key: int, api_version: int, correlation_id: int, client_id: str, body: bytes
) -> bytes:
    """Length-prefixed request with header v1."""
    hdr = (
        Writer()
        .i16(api_key)
        .i16(api_version)
        .i32(correlation_id)
        .string(client_id)
        .raw(body)
        .done()
    )
    return Writer().i32(len(hdr)).raw(hdr).done()
