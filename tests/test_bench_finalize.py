"""Exit-discipline contracts for the bench harness (round-3 verdict #1).

The driver's capture can kill bench.py at any moment (BENCH_r03.json:
rc 124, standing record "interim": true). These pin the fix: SIGTERM
finalizes the standing best artifact as a FINAL (non-interim) line and
exits 0; the wedge classifier and suite budget derive from one named
primary-cap constant; and the unmeasured Spark denominator carries an
explicitly-labeled bound instead of a bare null.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def test_suite_budget_derives_from_primary_cap():
    assert bench._SUITE_BUDGET == bench._PRIMARY_CAP + sum(
        s[1] for s in bench._SUITE_STAGES
    )


def test_default_budget_under_driver_timeout():
    # bench's BUILT-IN default must leave the driver's capture timeout
    # room to see a clean exit 0. Round 4 calibrated against an assumed
    # 2700s and the driver actually killed at 1798s — the budget must sit
    # under ~1700s so bench exits 0 on its own clock (round-4 verdict #1).
    assert bench._DEFAULT_BUDGET_S <= 1700


def test_baseline_bound_attached_and_labeled():
    result: dict = {}
    bench._attach_baseline_bound(result, build_s=100.0, nnz=25_000_000)
    bound = result["spark_baseline_bound"]
    # the analytic floor: 10 it x 2 sides x nnz x (2f^2 + 2f) / 200 GF/s
    expect_floor = 10 * 2.0 * 25e6 * (2 * 50**2 + 2 * 50) / 200e9
    assert bound["analytic_floor_seconds"] == round(expect_floor, 1)
    assert bound["speedup_vs_mllib_floor"] == round(expect_floor / 100.0, 2)
    # anchor scales linearly in interactions from the 25M range
    assert bound["literature_anchor_seconds"] == [300.0, 1800.0]
    assert bound["speedup_vs_mllib_anchor_range"] == [3.0, 18.0]
    # both must say what they are
    assert "anchor, not a measurement" in bound["literature_anchor_basis"]
    assert "optimistic" in bound["analytic_floor_basis"]
    assert "spark_baseline.py" in bound["command"]


def test_baseline_bound_without_build():
    result: dict = {}
    bench._attach_baseline_bound(result, build_s=None, nnz=1_000_000)
    bound = result["spark_baseline_bound"]
    assert "speedup_vs_mllib_floor" not in bound
    assert bound["literature_anchor_seconds"] == [12.0, 72.0]


def test_select_final_prefers_accel_partial_over_complete_cpu():
    # a 3-key wedged TPU partial must beat a bigger complete CPU anchor
    tpu = {"metric": "m", "value": 1.0, "platform": "tpu"}
    cpu = {
        "metric": "m_cpu", "value": 2.0, "platform": "cpu",
        "kernel_qps": 1.0, "als_build_seconds": 1.0, "scaling": [],
        "suite_complete": True,
    }
    best, is_cpu = bench._select_final(dict(tpu), None, dict(cpu))
    assert not is_cpu
    assert best["platform"] == "tpu"
    assert best["partial"] is True  # wedged mid-run: labeled


def test_select_final_complete_accel_not_marked_partial():
    tpu = {"metric": "m", "platform": "tpu", "suite_complete": True}
    best, is_cpu = bench._select_final(None, dict(tpu), None)
    assert not is_cpu
    assert "partial" not in best
    assert "suite_complete" not in best


def test_select_final_cpu_anchor_when_no_accel():
    # killed mid-CPU-suite (no suite_complete): labeled partial
    cpu = {"metric": "m_cpu", "platform": "cpu", "interim": True}
    best, is_cpu = bench._select_final(None, None, dict(cpu))
    assert is_cpu
    assert "interim" not in best
    assert best["partial"] is True
    # a complete CPU anchor is not partial
    done = {"metric": "m_cpu", "platform": "cpu", "suite_complete": True}
    best2, _ = bench._select_final(None, None, dict(done))
    assert "partial" not in best2 and "suite_complete" not in best2
    assert bench._select_final(None, None, None) == (None, True)


def test_select_final_ranks_by_stages_not_key_count():
    # round-4 advice (bench.py _select_final): an OLD wedged partial
    # carrying extra diagnostic keys must not outrank a NEWER artifact
    # that completed more stages but has fewer dict keys
    old_wide = {
        "metric": "m", "platform": "tpu", "stages_done": 2,
        "artifact_ts": 100.0, "suite_aborted_at": "x", "kernel_qps": 1.0,
        "extra_a": 1, "extra_b": 2, "extra_c": 3,
    }
    new_narrow = {
        "metric": "m", "platform": "tpu", "stages_done": 4,
        "artifact_ts": 200.0,
    }
    best, _ = bench._select_final(dict(old_wide), dict(new_narrow), None)
    assert best["stages_done"] == 4
    # recency breaks stage-count ties
    a = {"metric": "m", "platform": "tpu", "stages_done": 3, "artifact_ts": 1.0}
    b = {"metric": "m2", "platform": "tpu", "stages_done": 3, "artifact_ts": 2.0}
    best2, _ = bench._select_final(dict(a), dict(b), None)
    assert best2["metric"] == "m2"


def test_compact_summary_contract():
    """The LAST stdout line must always carry the driver's contract keys
    and stay small enough to survive a bounded tail capture — round 4's
    merged final line outgrew it and the record came back parsed: null."""
    result = {
        "metric": "als_recommend_http_qps_1M_items_50f", "value": 5000.0,
        "unit": "qps", "vs_baseline": 11.4, "platform": "tpu",
        "stages_done": 6, "lsh_qps": 40.0, "lsh_vs_baseline": 0.09,
        "scaling": [
            {"items": 10**6, "features": 50, "qps": 9000.0,
             "vs_lsh_baseline": 20.6, "mfu": 0.1, "compile_s": 3.0},
            {"items": 2 * 10**7, "features": 250, "qps": 100.0},
        ],
        "spark_baseline_bound": {
            "speedup_vs_mllib_floor": 2.5,
            "speedup_vs_mllib_anchor_range": [1.0, 6.0],
            "analytic_floor_basis": "long text " * 50,
        },
        "error": "w" * 1000 + " terminated by signal 15 end",
        "big_diag": ["x" * 100] * 50,  # detail-only ballast
    }
    s = bench._compact_summary(result)
    line = json.dumps(s)
    assert len(line) < 2000, len(line)
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in s
    assert s["final"] is True
    assert s["scaling_rows"] == 2
    assert s["scaling_best"]["vs_lsh_baseline"] == 20.6
    assert s["speedup_vs_mllib_anchor_range"] == [1.0, 6.0]
    # both ends of a long error survive truncation (the tail carries the
    # signal-finalization note the sigterm test pins)
    assert "terminated by signal 15" in s["error"]
    assert s["error"].startswith("w")
    assert "big_diag" not in s
    # degenerate artifact still carries the contract keys
    s2 = bench._compact_summary({"metric": "m", "value": 0.0, "unit": "qps"})
    assert s2["vs_baseline"] is None


def test_lsh_stage_registered_and_cpu_pinned():
    stages = {s[0]: s for s in bench._SUITE_STAGES}
    body, cap, allow_partial, merge, stage_cpu = stages["_bench_http_lsh_body"]
    assert stage_cpu is True  # host-CPU parity row, even in an accel suite
    result: dict = {}
    merge(result, {
        "value": 40.0, "vs_baseline": 0.09, "lsh_sample_rate": 0.3,
        "lsh_num_hashes": 2, "host_cores": 1,
        "qps_per_core_vs_baseline": 2.9, "latency_ms_p50": 11.0,
    })
    assert result["lsh_qps"] == 40.0
    assert result["lsh_vs_baseline"] == 0.09
    assert result["qps_per_core_vs_baseline"] == 2.9
    assert result["lsh_latency_ms_p50"] == 11.0


def test_sigterm_finalizes_standing_artifact_rc0():
    """Start bench.py, TERM it almost immediately, and require: exit 0,
    a FINAL last line (no interim flag), and the signal recorded in the
    error field — the driver's kill must never leave interim:true (or no
    line at all) as the round's standing record."""
    env = dict(os.environ)
    env["ORYX_BENCH_BUDGET_S"] = "120"
    env["ORYX_BENCH_POLL_S"] = "5"
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py")],
        cwd=str(REPO),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    time.sleep(2.0)
    proc.send_signal(signal.SIGTERM)
    try:
        out, _ = proc.communicate(timeout=90)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise AssertionError("bench.py did not exit after SIGTERM")
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out[-2000:]}"
    lines = [ln for ln in out.splitlines() if ln.strip().startswith("{")]
    assert lines, out[-2000:]
    final = json.loads(lines[-1])
    assert "interim" not in final
    assert "terminated by signal 15" in final.get("error", "")
    assert final["metric"].startswith("als_recommend")


def test_cpu_final_line_carries_banked_tpu_window(tmp_path, monkeypatch):
    """A forced-CPU run's final line must still surface the last measured
    TPU window (committed BENCH_TPU_WINDOW_r*.json), provenance-labeled —
    the chip wedging before the driver's run must not erase the round's
    hardware evidence."""
    import json as _json

    import bench

    doc = {
        "captured_at": "2026-01-01T00:00:00Z",
        "final": {
            "metric": "m", "value": 123.0, "vs_baseline": 9.9,
            "pallas_speedup": 1.5,
            "scaling_best": {"items": 10, "qps": 5.0},
        },
    }
    (tmp_path / "BENCH_TPU_WINDOW_r99.json").write_text(_json.dumps(doc))
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    s = bench._compact_summary(
        {"platform": "cpu", "metric": "x", "value": 1.0, "unit": "qps",
         "vs_baseline": 0.1}
    )
    w = s["last_tpu_window"]
    assert w["value"] == 123.0 and w["vs_baseline"] == 9.9
    assert "NOT from this" in w["note"]
    # a malformed LATER artifact must neither break final-line emission
    # nor shadow the good banked window (best-across-files, not newest)
    (tmp_path / "BENCH_TPU_WINDOW_r100.json").write_text("[]")
    s3 = bench._compact_summary(
        {"platform": "cpu", "metric": "x", "value": 1.0, "unit": "qps",
         "vs_baseline": 0.1}
    )
    assert s3["final"] and s3["last_tpu_window"]["value"] == 123.0
    # a LATER but worse (fewer-stage) window must not shadow it either
    worse = {"final": {"metric": "m", "value": 1.0, "vs_baseline": 0.2,
                       "stages_done": 0}}
    (tmp_path / "BENCH_TPU_WINDOW_r101.json").write_text(_json.dumps(worse))
    s4 = bench._compact_summary(
        {"platform": "cpu", "metric": "x", "value": 1.0, "unit": "qps",
         "vs_baseline": 0.1}
    )
    assert s4["last_tpu_window"]["value"] == 123.0
    # a TPU run does not attach it
    s2 = bench._compact_summary(
        {"platform": "tpu", "metric": "x", "value": 1.0, "unit": "qps",
         "vs_baseline": 2.0}
    )
    assert "last_tpu_window" not in s2
