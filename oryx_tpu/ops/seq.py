"""Next-item sequence model kernels: a compact GRU over item embeddings.

The fourth packaged app's device math (ROADMAP item 4). One recurrent
cell, embedding-tied output — logits for "which item comes next" are
``h @ E.T`` over the SAME item-embedding matrix the inputs gather from —
so the serving layer scores the whole catalog with exactly the top-k
matmul shape the ALS path already dispatches through the micro-batcher
(serving/batcher.py): the hidden state is the "user vector", E is the
"item matrix", and score modes / shedding / perfstats all come for free.

Training is minibatched softmax cross-entropy with an Adagrad step,
``lax.scan`` over the window inside one jitted step function, and the
same prediction-convergence early stop discipline ALS warm starts use
(ml/update.py lineage): relative change of sampled next-item scores, not
parameter norms — embeddings keep drifting along directions the
predictions no longer care about.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

# Non-embedding GRU parameter names, in artifact/tensor order. The
# embedding matrix "E" rides separately: it is also the serving catalog
# (streamed row-by-row as UP messages, like ALS factor rows).
GRU_PARAM_NAMES = ("Wx", "Wh", "b")


class GruModel(NamedTuple):
    """A trained next-item model: item embeddings + recurrent weights."""

    e: np.ndarray          # [V, d] item embeddings (also the output table)
    params: dict           # Wx [d,3d], Wh [d,3d], b [3d]
    item_ids: list         # [V] row-aligned item id strings


def init_gru_params(key, dim: int) -> dict:
    """Recurrent weights at 1/sqrt(d) scale; gate order is (z, r, n)."""
    kx, kh = jax.random.split(key)
    s = 1.0 / math.sqrt(dim)
    return {
        "Wx": np.array(jax.random.normal(kx, (dim, 3 * dim)) * s, dtype=np.float32),
        "Wh": np.array(jax.random.normal(kh, (dim, 3 * dim)) * s, dtype=np.float32),
        "b": np.zeros(3 * dim, dtype=np.float32),
    }


def _gru_cell(params, x, h):
    """One GRU step: x [B,d] inputs, h [B,d] state -> new state."""
    d = h.shape[-1]
    gx = x @ params["Wx"] + params["b"]
    gh = h @ params["Wh"]
    z = jax.nn.sigmoid(gx[:, :d] + gh[:, :d])
    r = jax.nn.sigmoid(gx[:, d : 2 * d] + gh[:, d : 2 * d])
    n = jnp.tanh(gx[:, 2 * d :] + r * gh[:, 2 * d :])
    return (1.0 - z) * n + z * h


def _encode_embedded(params, xs, mask):
    """Scan the cell over time: xs [B,L,d] embedded inputs, mask [B,L]
    (1 = real event, 0 = left padding); returns final h [B,d]. Masked
    steps carry the state through unchanged, so short sessions and full
    windows share one compiled program."""

    def step(h, xm):
        x, m = xm
        h2 = _gru_cell(params, x, h)
        return jnp.where(m[:, None] > 0, h2, h), None

    h0 = jnp.zeros((xs.shape[0], xs.shape[2]), dtype=xs.dtype)
    h, _ = jax.lax.scan(step, h0, (jnp.swapaxes(xs, 0, 1), mask.T))
    return h


@jax.jit
def encode_vectors(params, xs, mask):
    """Jitted session encoder over pre-gathered embedding vectors —
    the serving path's form: the request carries item ids, the caller
    gathers their rows from the factor store, no vocab table needed."""
    return _encode_embedded(params, xs, mask)


def _encode_idx(params, e, idx, mask):
    return _encode_embedded(params, e[idx], mask)


def _nll(weights, idx, mask, targets):
    """Mean next-item negative log-likelihood of a minibatch under the
    embedding-tied softmax (logits = h @ E.T)."""
    e, params = weights["E"], weights
    h = _encode_idx(params, e, idx, mask)
    logits = h @ e.T
    return -jnp.mean(
        jax.nn.log_softmax(logits, axis=-1)[jnp.arange(idx.shape[0]), targets]
    )


@jax.jit
def _adagrad_step(weights, accum, idx, mask, targets, lr):
    """One minibatch step; returns (weights, accum, loss). Adagrad keeps
    the per-parameter scale adaptive with only the accumulator as state
    — which train_gru seeds at 0 for cold starts and at 1.0 for warm
    resumes (see the accum_0 comment there: a zero restart takes
    lr-sized sign steps that re-shock a converged model)."""
    loss, grads = jax.value_and_grad(_nll)(weights, idx, mask, targets)
    new_w, new_a = {}, {}
    for k in weights:
        g = grads[k]
        a = accum[k] + g * g
        new_w[k] = weights[k] - lr * g / jnp.sqrt(a + 1e-8)
        new_a[k] = a
    return new_w, new_a, loss


@jax.jit
def _sampled_scores(weights, idx, mask, targets):
    """Predicted scores of the true next items on a fixed probe sample —
    the convergence signal (prediction space, not parameter space)."""
    h = _encode_idx(weights, weights["E"], idx, mask)
    return jnp.sum(h * weights["E"][targets], axis=-1)


def train_gru(
    contexts: np.ndarray,
    mask: np.ndarray,
    targets: np.ndarray,
    n_items: int,
    dim: int,
    item_ids,
    epochs: int = 30,
    lr: float = 0.5,
    batch: int = 1024,
    seed_key=None,
    resume_e: np.ndarray | None = None,
    resume_params: dict | None = None,
    tol: float = 0.0,
    min_epochs: int = 2,
    check_every: int = 2,
    probe: int = 512,
) -> tuple[GruModel, int]:
    """Train the next-item GRU; returns (model, epochs actually run).

    contexts [N,L] int32 item rows (left-padded), mask [N,L], targets [N]
    item rows. resume_e/resume_params warm-start from the previous
    generation (ids already aligned by the caller via ops/als.py
    align_factors); tol > 0 enables the prediction-convergence early stop
    checked every ``check_every`` epochs after ``min_epochs``.
    """
    n = int(contexts.shape[0])
    if n == 0 or n_items == 0:
        raise ValueError("no training examples")
    key = seed_key
    if key is None:
        from oryx_tpu.common.rng import RandomManager

        key = RandomManager.get_key()
    k_e, k_p, k_s = jax.random.split(key, 3)
    if resume_e is not None and resume_e.shape == (n_items, dim):
        e0 = np.asarray(resume_e, dtype=np.float32)
    else:
        e0 = np.array(
            jax.random.normal(k_e, (n_items, dim)) * (1.0 / math.sqrt(dim)),
            dtype=np.float32,
        )
    params = (
        {k: np.asarray(v, dtype=np.float32) for k, v in resume_params.items()}
        if resume_params is not None
        and all(k in resume_params for k in GRU_PARAM_NAMES)
        and np.shape(resume_params.get("Wh")) == (dim, 3 * dim)
        else init_gru_params(k_p, dim)
    )
    weights = {"E": jnp.asarray(e0), **{k: jnp.asarray(params[k]) for k in GRU_PARAM_NAMES}}
    # Warm resumes seed the Adagrad accumulator at 1.0 instead of 0: a
    # zero accumulator makes every first step lr-sized REGARDLESS of the
    # gradient (sign steps), which re-shocks a converged model for
    # several epochs before the prediction-convergence stop can fire;
    # with the floor, steps near convergence are ~lr·g — small where the
    # model is already right, full-sized where the new window disagrees.
    accum_0 = 1.0 if resume_e is not None and resume_params is not None else 0.0
    accum = {k: jnp.full_like(v, accum_0) for k, v in weights.items()}

    batch = max(1, min(batch, n))
    # fixed probe sample for the convergence signal (deterministic)
    rng = np.random.default_rng(int(jax.random.randint(k_s, (), 0, 1 << 30)))
    probe_rows = rng.choice(n, size=min(probe, n), replace=False)
    p_idx = jnp.asarray(contexts[probe_rows])
    p_mask = jnp.asarray(mask[probe_rows])
    p_tgt = jnp.asarray(targets[probe_rows])

    lr_j = jnp.float32(lr)
    prev_scores = None
    ran = 0
    for epoch in range(max(1, int(epochs))):
        order = rng.permutation(n)
        for lo in range(0, n, batch):
            rows = order[lo : lo + batch]
            if len(rows) < batch:  # pad to the compiled batch shape
                rows = np.concatenate([rows, order[: batch - len(rows)]])
            weights, accum, _ = _adagrad_step(
                weights, accum,
                jnp.asarray(contexts[rows]), jnp.asarray(mask[rows]),
                jnp.asarray(targets[rows]), lr_j,
            )
        ran = epoch + 1
        if tol > 0 and ran >= min_epochs and ran % max(1, check_every) == 0:
            scores = np.asarray(_sampled_scores(weights, p_idx, p_mask, p_tgt))
            if prev_scores is not None:
                denom = float(np.linalg.norm(prev_scores)) or 1.0
                rel = float(np.linalg.norm(scores - prev_scores)) / denom
                if rel < tol:
                    break
            prev_scores = scores
    model = GruModel(
        e=np.asarray(weights["E"], dtype=np.float32),
        params={k: np.asarray(weights[k], dtype=np.float32) for k in GRU_PARAM_NAMES},
        item_ids=list(item_ids),
    )
    return model, ran


def next_item_hit_rate(
    e: np.ndarray,
    params: dict,
    contexts: np.ndarray,
    mask: np.ndarray,
    targets: np.ndarray,
    k: int = 10,
    chunk: int = 2048,
) -> float:
    """Mean hit-rate@k over next-item examples: the fraction whose true
    next item lands in the model's top-k — the ONE definition the batch
    eval, the quality gate, and the bench's seq stage all share. NaN when
    there is nothing to evaluate."""
    n = int(contexts.shape[0])
    if n == 0:
        return float("nan")
    e_j = jnp.asarray(np.asarray(e, dtype=np.float32))
    jp = {name: jnp.asarray(np.asarray(params[name], dtype=np.float32))
          for name in GRU_PARAM_NAMES}
    k = min(k, int(e.shape[0]))
    hits = 0
    for lo in range(0, n, chunk):
        h = encode_vectors(
            jp, e_j[jnp.asarray(contexts[lo : lo + chunk])],
            jnp.asarray(mask[lo : lo + chunk]),
        )
        logits = np.asarray(h @ e_j.T)
        top = np.argpartition(-logits, k - 1, axis=1)[:, :k]
        hits += int((top == targets[lo : lo + chunk, None]).any(axis=1).sum())
    return hits / n


def encode_sessions(params: dict, item_vectors: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Host-friendly wrapper: encode pre-gathered [B,L,d] item vectors
    (zeros on padded steps) into [B,d] hidden states."""
    jp = {k: jnp.asarray(np.asarray(params[k], dtype=np.float32)) for k in GRU_PARAM_NAMES}
    return np.asarray(
        encode_vectors(
            jp,
            jnp.asarray(np.asarray(item_vectors, dtype=np.float32)),
            jnp.asarray(np.asarray(mask, dtype=np.float32)),
        )
    )
