"""Parallel task fan-out helpers.

Mirrors the reference's ExecUtils (framework/oryx-common
.../lang/ExecUtils.java:32-75): run N tasks at parallelism P, optionally on a
private pool, collecting results. Used by the ML harness to build and
evaluate hyperparameter candidates concurrently (MLUpdate.java:253-258).
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence, TypeVar

log = logging.getLogger(__name__)

T = TypeVar("T")


def do_in_parallel(
    num_tasks: int,
    task: Callable[[int], None],
    parallelism: int | None = None,
) -> None:
    collect_in_parallel(num_tasks, task, parallelism)


def collect_in_parallel(
    num_tasks: int,
    task: Callable[[int], T],
    parallelism: int | None = None,
) -> list[T]:
    """Run task(0..num_tasks-1), at most `parallelism` at a time, returning
    results in index order. parallelism<=1 runs inline (no pool), which
    matters on TPU where concurrent jitted builds would contend for the
    device — the harness defaults to sequential candidate builds."""
    if num_tasks <= 0:
        return []
    parallelism = min(parallelism or 1, num_tasks)
    if parallelism <= 1:
        return [task(i) for i in range(num_tasks)]
    with ThreadPoolExecutor(max_workers=parallelism) as pool:
        return list(pool.map(task, range(num_tasks)))


def map_in_parallel(items: Sequence[T], fn: Callable[[T], "T"], parallelism: int = 4) -> list:
    return collect_in_parallel(len(items), lambda i: fn(items[i]), parallelism)


class LoggingRunnable:
    """Wrap a callable so exceptions are logged, not swallowed by executor
    futures (reference LoggingCallable)."""

    def __init__(self, fn: Callable[[], None], name: str = "task"):
        self.fn = fn
        self.name = name

    def __call__(self) -> None:
        try:
            self.fn()
        except Exception:  # noqa: BLE001 - must log whatever escapes a thread
            log.exception("unexpected error in %s", self.name)
            raise


def free_port_run(n: int, host: str = "127.0.0.1", attempts: int = 50) -> int:
    """Base of a run of ``n`` consecutive free TCP ports on ``host`` —
    the shape a fleet supervisor's ``base-port + i`` layout needs. All
    ``n`` ports are held bound while probing so the run is free at the
    moment of return (the usual bind race remains: the caller must bind
    soon after)."""
    import socket

    for _ in range(attempts):
        socks: list[socket.socket] = []
        try:
            s = socket.socket()
            s.bind((host, 0))
            base = s.getsockname()[1]
            socks.append(s)
            for i in range(1, n):
                si = socket.socket()
                si.bind((host, base + i))
                socks.append(si)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"no run of {n} free ports found on {host}")


def config_overlay_from_sets(pairs) -> dict:
    """``key=value`` strings (the CLI's ``--set`` grammar) as a config
    overlay dict: values parse as JSON where possible (numbers, bools,
    lists) and fall back to raw strings — exactly how cli.py applies
    ``--set``, shared here so harnesses building a Config AND a child
    argv from one list of sets cannot drift from the CLI's coercion."""
    import json

    out: dict = {}
    for s in pairs:
        k, v = s.split("=", 1)
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v
    return out


def cpu_subprocess_env(base: dict | None = None, **overrides: str) -> dict:
    """Environment for a CPU-only child python process: forces
    JAX_PLATFORMS=cpu and strips accelerator-plugin triggers. A
    sitecustomize-registered device transport dials the accelerator at
    interpreter startup, and a wedged transport then hangs even CPU-only
    children at import (observed on the round-1 bench host) — a child that
    will never use the device must not inherit the trigger."""
    import os

    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(overrides)
    return env
