"""Sub-mesh partitioning for parallel hyperparameter candidates.

The reference builds and evaluates model candidates concurrently on the
Spark cluster (framework/oryx-ml .../ml/MLUpdate.java:253-258,
ExecUtils.collectInParallel with oryx.ml.eval.parallelism). The TPU-native
equivalent cannot just thread the builds over ONE mesh — concurrent
programs on the same devices merely contend, and on a multi-member pod
they interleave collectives in thread-scheduling order and wedge the
group. Instead the device mesh is PARTITIONED along its data axis into
disjoint sub-meshes, one candidate per sub-mesh: each candidate's
collectives run entirely inside its own device group, so the builds are
truly concurrent and cannot deadlock each other.

The active sub-mesh travels to the app's trainer through a thread-local
(the build threads of oryx_tpu/ml/update.py each enter candidate_mesh());
apps resolve it via MLUpdate._build_mesh() at build time.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from jax.sharding import Mesh

from oryx_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

_TLS = threading.local()


def current_candidate_mesh() -> Mesh | None:
    """The sub-mesh assigned to the candidate building on THIS thread, or
    None outside a partitioned build."""
    return getattr(_TLS, "mesh", None)


@contextmanager
def candidate_mesh(mesh: Mesh | None):
    prev = getattr(_TLS, "mesh", None)
    _TLS.mesh = mesh
    try:
        yield
    finally:
        _TLS.mesh = prev


def partition_mesh(mesh: Mesh, k: int) -> list[Mesh]:
    """Split a (data, model) mesh into up to k disjoint sub-meshes along
    the data axis (contiguous slices, sizes as equal as possible; the
    model axis is kept whole inside every sub-mesh — tensor-parallel
    candidates stay tensor-parallel). Returns fewer than k meshes when
    the data axis has fewer rows than k; a 1-row data axis returns the
    whole mesh (nothing to partition)."""
    if k <= 1:
        return [mesh]
    d = mesh.devices.shape[0]
    k = min(k, d)
    if k <= 1:
        return [mesh]
    base, extra = divmod(d, k)
    subs: list[Mesh] = []
    row = 0
    for g in range(k):
        rows = base + (1 if g < extra else 0)
        subs.append(
            Mesh(mesh.devices[row : row + rows, :], (DATA_AXIS, MODEL_AXIS))
        )
        row += rows
    return subs
