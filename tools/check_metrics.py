#!/usr/bin/env python
"""Static metric-name consistency check — thin wrapper (DEPRECATED entry
point; the logic now lives in the oryxlint ``metric-docs`` and
``bench-ratchet`` rules, tools/oryxlint/checkers/consistency.py, and
runs with the rest of the static-analysis suite via
``python -m tools.oryxlint``).

Kept as a CLI because operators and older docs invoke it directly. The
collector functions (``code_metric_names``, ``doc_metric_names``) are
defined here and stay monkeypatchable as before — ``main`` reads them
through this module's globals. ``VALID_NAME`` and friends are read-only
re-exports of the rule's constants (rebinding them here does not change
the rule's behavior).

Contract (unchanged): every ``oryx_``-prefixed string literal under
``oryx_tpu/`` matches ``^oryx_[a-z0-9_]+$`` and has a reference-table
row in ``docs/observability.md`` (and vice versa); every metric name
ratcheted in ``BASELINE_RATCHET.json`` still exists in ``bench.py``'s
output vocabulary; the score-mode bench/doc vocabulary is present.

Exit status 0 = consistent; 1 = drift (each problem printed on stderr).
"""

from __future__ import annotations

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "oryx_tpu"
DOC = ROOT / "docs" / "observability.md"

if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from tools.oryxlint.checkers import consistency as _rule  # noqa: E402

# re-exported for callers/tests that reach into this module
VALID_NAME = _rule.VALID_METRIC_NAME
CODE_LITERAL = _rule.METRIC_LITERAL
DOC_ROW = _rule.DOC_ROW
IGNORE = _rule.METRIC_IGNORE
REQUIRED_BENCH_FIELDS = _rule.REQUIRED_BENCH_FIELDS
REQUIRED_DOC_TOKENS = _rule.REQUIRED_DOC_TOKENS
REQUIRED_PERFATTR_FAMILIES = _rule.REQUIRED_PERFATTR_FAMILIES


def code_metric_names() -> dict[str, str]:
    """name -> first file using it, for every metric-shaped literal."""
    return {
        name: where
        for name, (where, _line) in _rule.code_metric_names(PACKAGE, ROOT).items()
    }


def doc_metric_names() -> set[str]:
    return _rule.doc_metric_names(DOC)


def vocabulary_problems() -> list[str]:
    import re

    problems = []
    bench_text = (ROOT / "bench.py").read_text(encoding="utf-8")
    for name in REQUIRED_BENCH_FIELDS:
        if not re.search(rf'"{re.escape(name)}"', bench_text):
            problems.append(
                f"{name}: required bench vocabulary missing from bench.py"
            )
    doc_text = DOC.read_text(encoding="utf-8")
    for tok in REQUIRED_DOC_TOKENS:
        if tok not in doc_text:
            problems.append(
                f"{tok}: required label name missing from docs/observability.md"
            )
    return problems


def ratchet_problems() -> list[str]:
    """Ratcheted names must exist in bench.py; stale pending rows fail
    (tools/check_bench.stale_pending_problems) — rendered through the
    oryxlint rule so both CLIs and the tier-1 lint agree."""
    return [
        f.message for f in _rule.ratchet_findings(ROOT)
    ]


def main() -> int:
    problems: list[str] = []
    if not DOC.exists():
        print(f"missing {DOC.relative_to(ROOT)}", file=sys.stderr)
        return 1
    problems.extend(
        _rule.metric_doc_problems(code_metric_names(), doc_metric_names())
    )
    problems.extend(ratchet_problems())
    problems.extend(vocabulary_problems())
    for p in problems:
        print(p, file=sys.stderr)
    if not problems:
        print("ok: metric names consistent with docs")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
