"""Vector/matrix primitives.

The reference's VectorMath (framework/oryx-common .../math/VectorMath.java:
37-129: dot, norm, cosineSimilarity, transposeTimesSelf, randomVectorF) as
jitted JAX ops. transposeTimesSelf — the Gram matrix X^T.X that ALS needs
every half-iteration — is here a single einsum: under pjit with X sharded
over the "data" axis XLA lowers it to per-shard matmuls + psum, which is
exactly the partition-sum the reference hand-rolled in
PartitionedFeatureVectors.getVTV (…/als/PartitionedFeatureVectors.java:209-213).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from oryx_tpu.common.rng import RandomManager


@jax.jit
def dot(x, y):
    return jnp.vdot(x, y)


@jax.jit
def norm(x):
    return jnp.linalg.norm(x)


@jax.jit
def cosine_similarity(x, y, norm_y=None):
    ny = jnp.linalg.norm(y) if norm_y is None else norm_y
    return jnp.vdot(x, y) / (jnp.linalg.norm(x) * ny)


@jax.jit
def gram(x):
    """X^T.X in float32 accumulation (bf16-friendly inputs upcast)."""
    x = x.astype(jnp.float32)
    return jnp.einsum("uk,ul->kl", x, x, precision=jax.lax.Precision.HIGHEST)


def random_unit_vectors(n: int, dim: int, key=None):
    """n random unit-norm rows (VectorMath.randomVectorF + normalization),
    used for LSH hyperplanes and factor init."""
    key = key if key is not None else RandomManager.get_key()
    v = jax.random.normal(key, (n, dim), dtype=jnp.float32)
    return v / jnp.linalg.norm(v, axis=1, keepdims=True)
