"""In-process broker: the test-fixture backbone.

The reference's tests boot a real single-node Kafka broker inside the JVM
(framework/kafka-util src/test LocalKafkaBroker.java:44-60); this broker
plays that role in-process — a full implementation of the Broker contract
(partitions, offsets, groups), just backed by lists under a lock, shared by
name so producer and consumer code in different threads meet at `mem://x`.
"""

from __future__ import annotations

import threading
from typing import Mapping

from oryx_tpu.bus.broker import Broker, partition_for


class InProcBroker(Broker):
    _registry: dict[str, "InProcBroker"] = {}
    _registry_lock = threading.Lock()

    @classmethod
    def named(cls, name: str) -> "InProcBroker":
        with cls._registry_lock:
            if name not in cls._registry:
                cls._registry[name] = InProcBroker()
            return cls._registry[name]

    @classmethod
    def reset_all(cls) -> None:
        with cls._registry_lock:
            cls._registry.clear()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # topic -> list of partitions, each a list of (key, message)
        self._logs: dict[str, list[list[tuple[str | None, str]]]] = {}
        self._max_bytes: dict[str, int] = {}
        # (group, topic) -> {partition: offset}
        self._offsets: dict[tuple[str, str], dict[int, int]] = {}

    # -- admin -------------------------------------------------------------

    def create_topic(self, topic: str, partitions: int = 1, max_message_bytes: int = 1 << 24) -> None:
        with self._lock:
            if topic in self._logs:
                raise ValueError(f"topic exists: {topic}")
            self._logs[topic] = [[] for _ in range(max(1, partitions))]
            self._max_bytes[topic] = max_message_bytes

    def topic_exists(self, topic: str) -> bool:
        with self._lock:
            return topic in self._logs

    def delete_topic(self, topic: str) -> None:
        with self._lock:
            self._logs.pop(topic, None)
            self._max_bytes.pop(topic, None)
            for k in [k for k in self._offsets if k[1] == topic]:
                del self._offsets[k]

    def num_partitions(self, topic: str) -> int:
        with self._lock:
            self._check(topic)
            return len(self._logs[topic])

    # -- data --------------------------------------------------------------

    def send(self, topic: str, key: str | None, message: str, partition: int | None = None) -> None:
        with self._lock:
            self._check(topic)
            parts = self._logs[topic]
            if len(message.encode("utf-8")) > self._max_bytes[topic]:
                raise ValueError(f"message exceeds max size for {topic}")
            p = partition if partition is not None else partition_for(key, len(parts))
            parts[p].append((key, message))

    def read(self, topic: str, partition: int, offset: int, max_records: int) -> list[tuple[int, str | None, str]]:
        with self._lock:
            self._check(topic)
            log = self._logs[topic][partition]
            chunk = log[offset : offset + max_records]
            return [(offset + i, k, m) for i, (k, m) in enumerate(chunk)]

    def end_offsets(self, topic: str) -> list[int]:
        with self._lock:
            self._check(topic)
            return [len(p) for p in self._logs[topic]]

    # -- offsets -----------------------------------------------------------

    def commit_offsets(self, group: str, topic: str, offsets: Mapping[int, int]) -> None:
        with self._lock:
            self._offsets.setdefault((group, topic), {}).update(offsets)

    def get_offsets(self, group: str, topic: str) -> dict[int, int]:
        with self._lock:
            return dict(self._offsets.get((group, topic), {}))

    def _check(self, topic: str) -> None:
        if topic not in self._logs:
            raise KeyError(f"no such topic: {topic}")
