"""Synthetic MovieLens-shaped interaction data, shared by the bench and
the Spark-MLlib baseline runner (tools/spark_baseline.py).

The bench host has no dataset egress, so the ALS north-star measurement
(BASELINE.json: model-build wall-clock at MovieLens-25M scale) runs on
data synthesized to the ML-25M shape: ~162k users x 59k items x 25M
interactions, Zipf-skewed item popularity, log-normal user activity.
Both the TPU build and the Spark baseline MUST consume this exact
generator with the same seed — otherwise the speedup ratio compares two
different problems.

Planted latent structure: users and items carry genres and most of a
user's interactions stay inside their genre. Without structure the
held-out AUC hovers near the popularity baseline and says nothing about
model quality; with it a well-trained model must clear ~0.8, so the
reported AUC is a real quality signal.
"""

from __future__ import annotations

import numpy as np


def synthesize_interactions(
    n_users: int,
    n_items: int,
    nnz: int,
    seed: int = 7,
    n_genres: int = 32,
    in_genre_p: float = 0.8,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Return (users, items, values): nnz interactions with ML-25M-like
    marginals and planted genre structure. Deterministic in seed."""
    rng = np.random.default_rng(seed)
    item_w = 1.0 / np.power(np.arange(1, n_items + 1), 0.9)
    item_w /= item_w.sum()
    user_w = rng.lognormal(0.0, 1.1, n_users)
    user_w /= user_w.sum()
    item_genre = rng.integers(0, n_genres, n_items)
    user_genre = rng.integers(0, n_genres, n_users)
    users = rng.choice(n_users, size=nnz, p=user_w).astype(np.int64)
    items = rng.choice(n_items, size=nnz, p=item_w).astype(np.int64)
    # redraw the in-genre portion from the user's own genre, popularity-
    # weighted within it (one vectorized choice per genre)
    in_genre = rng.random(nnz) < in_genre_p
    ug = user_genre[users]
    for g in range(n_genres):
        rows = np.nonzero(in_genre & (ug == g))[0]
        pool = np.nonzero(item_genre == g)[0]
        if rows.size == 0 or pool.size == 0:
            continue
        w = item_w[pool] / item_w[pool].sum()
        items[rows] = rng.choice(pool, size=rows.size, p=w)
    values = rng.choice(
        [0.5, 1, 1.5, 2, 2.5, 3, 3.5, 4, 4.5, 5], size=nnz
    ).astype(np.float64)
    return users, items, values
