#!/usr/bin/env python
"""Fleet load harness: drive a running fleet front, report both sides.

`cli loadtest` measures one serving process; this harness measures the
FLEET — it generates the recommend traffic itself (many distinct users,
so consistent-hash placement actually spreads), drives the front, and
then reads the front's own books: per-replica request distribution,
retries (shed / connect), ejections, generation skew, and each
replica's probe snapshot from ``/fleet/status``. A deliberate shed
(503 + Retry-After surfacing after every replica shed) is counted
separately from real errors, per the PR 5 contract.

Two drive modes:

- **closed-loop** (default): N workers, each fires its next request the
  moment the previous answer lands — throughput self-throttles to the
  fleet's capacity, the classic saturation probe.
- **open-loop** (``--arrival-rate``): arrivals are scheduled in advance
  from a Poisson process at the offered rate and fired ON TIME whether
  or not earlier requests finished — the shape real traffic has, and
  the only shape that exercises the autoscaler honestly (a closed loop
  slows down exactly when the fleet does, hiding the backlog the
  scaler exists to absorb). ``--pattern`` shapes the offered rate
  (``uniform`` | ``diurnal`` sinusoid | ``bursty`` on/off square wave)
  and ``--user-dist zipf`` skews the user ids so a hot-key cohort
  hammers one hash-placement replica.

    python -m oryx_tpu.cli fleet --conf oryx.conf --replicas 2 &
    python tools/fleetload.py --url http://localhost:8090 --duration 20 \\
        --arrival-rate 200 --pattern bursty --user-dist zipf

Prints ONE JSON report line. Exit status 1 when any non-shed error was
observed (the fleet contract: a healthy fleet behind the front serves
every request or sheds it honestly).
"""

from __future__ import annotations

import argparse
import bisect
import http.client
import json
import math
import os
import random
import re
import sys
import threading
import time
from urllib.parse import urlsplit

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _scrape(host: str, port: int, path: str) -> tuple[int, str]:
    conn = http.client.HTTPConnection(host, port, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read().decode("utf-8", "replace")
    finally:
        conn.close()


def _front_books(host: str, port: int) -> dict:
    """The front's own view of the run: /fleet/status + the
    oryx_fleet_* families off its /metrics."""
    out: dict = {}
    try:
        status, body = _scrape(host, port, "/fleet/status")
        if status == 200:
            out.update(json.loads(body))
    except Exception as e:  # noqa: BLE001 - report what we can
        out["status_error"] = f"{type(e).__name__}: {e}"
    try:
        _, text = _scrape(host, port, "/metrics")
        by_replica: dict[str, float] = {}
        retries: dict[str, float] = {}
        ejections: dict[str, float] = {}
        for line in text.splitlines():
            m = re.match(
                r'oryx_fleet_front_requests_total\{replica="([^"]+)"\} (\S+)',
                line,
            )
            if m:
                by_replica[m.group(1)] = float(m.group(2))
                continue
            m = re.match(
                r'oryx_fleet_front_retries_total\{reason="([^"]+)"\} (\S+)',
                line,
            )
            if m:
                retries[m.group(1)] = float(m.group(2))
                continue
            m = re.match(
                r'oryx_fleet_ejections_total\{replica="([^"]+)"\} (\S+)', line
            )
            if m:
                ejections[m.group(1)] = float(m.group(2))
                continue
            if line.startswith("oryx_fleet_generation_skew "):
                out["generation_skew"] = float(line.split()[1])
        if by_replica:
            out["requests_by_replica"] = {
                k: int(v) for k, v in sorted(by_replica.items())
            }
        if retries:
            out["retries"] = {k: int(v) for k, v in sorted(retries.items())}
        if ejections:
            out["ejections"] = {k: int(v) for k, v in sorted(ejections.items())}
    except Exception as e:  # noqa: BLE001
        out["metrics_error"] = f"{type(e).__name__}: {e}"
    return out


def _rate_at(base: float, pattern: str, period: float, t: float) -> float:
    """Instantaneous offered rate (req/s) at offset ``t`` into the run."""
    if pattern == "diurnal":
        # one sinusoidal "day" per period: trough at 20% of base, peak
        # at 180% — the autoscaler should ride it down and back up
        return max(0.2 * base, base * (1.0 + 0.8 * math.sin(2.0 * math.pi * t / period)))
    if pattern == "bursty":
        # on/off square wave: 20% of each period at 4x, the rest at a
        # quarter rate — mean stays ~base, peaks probe shed + scale-up
        return 4.0 * base if (t % period) < 0.2 * period else 0.25 * base
    return base


def _zipf_picker(n: int, s: float, rng: random.Random):
    """Bounded Zipf(s) sampler over ranks [0, n): precompute the harmonic
    CDF once, then bisect per draw. Low ranks are the hot keys — with
    hash placement they concentrate on few replicas, the worst case for
    the canary cohort split and for scale-down victim choice."""
    cdf: list[float] = []
    total = 0.0
    for k in range(1, n + 1):
        total += 1.0 / k**s
        cdf.append(total)

    def pick() -> int:
        return bisect.bisect_left(cdf, rng.random() * total)

    return pick


def _build_arrivals(args, rng: random.Random) -> list[tuple[float, str]]:
    """Pre-draw the whole open-loop schedule: (offset_s, path) pairs from
    a non-homogeneous Poisson process. Pre-drawing keeps the hot path a
    sleep + one request — no clock math races the fleet under test."""
    if args.user_dist == "zipf":
        pick_user = _zipf_picker(args.users, args.zipf_s, rng)
    else:
        pick_user = lambda: rng.randrange(args.users)
    period = args.pattern_period or args.duration
    arrivals: list[tuple[float, str]] = []
    t = 0.0
    while True:
        t += rng.expovariate(_rate_at(args.arrival_rate, args.pattern, period, t))
        if t >= args.duration:
            return arrivals
        arrivals.append(
            (t, f"/recommend/u{pick_user()}?howMany={args.how_many}")
        )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--url", default="http://localhost:8090",
        help="base URL of a running fleet front (default the front's "
        "default port)",
    )
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument(
        "--workers", type=int, default=16,
        help="concurrent client connections (closed-loop: one stream "
        "each; open-loop: the pool that fires scheduled arrivals)",
    )
    ap.add_argument(
        "--users", type=int, default=10_000,
        help="distinct user ids in the generated /recommend traffic "
        "(hash placement needs many to spread)",
    )
    ap.add_argument("--how-many", type=int, default=10)
    ap.add_argument(
        "--arrival-rate", type=float, default=None,
        help="switch to open-loop mode: offered request rate in req/s; "
        "arrivals are scheduled from a Poisson process and fired on "
        "time regardless of response latency",
    )
    ap.add_argument(
        "--pattern", choices=("uniform", "diurnal", "bursty"),
        default="uniform",
        help="open-loop rate shape: uniform, diurnal (sinusoid over "
        "--pattern-period), or bursty (on/off square wave)",
    )
    ap.add_argument(
        "--pattern-period", type=float, default=None,
        help="seconds per diurnal/bursty cycle (default: the whole run "
        "is one cycle)",
    )
    ap.add_argument(
        "--user-dist", choices=("uniform", "zipf"), default="uniform",
        help="open-loop user-id distribution; zipf concentrates traffic "
        "on hot keys so hash placement loads few replicas",
    )
    ap.add_argument(
        "--zipf-s", type=float, default=1.1,
        help="Zipf exponent for --user-dist zipf (higher = hotter head)",
    )
    ap.add_argument(
        "--seed", type=int, default=0,
        help="RNG seed for the open-loop schedule (reproducible runs)",
    )
    args = ap.parse_args()

    split = urlsplit(args.url if "//" in args.url else f"http://{args.url}")
    host, port = split.hostname or "localhost", split.port or 8090
    n_workers = max(1, args.workers)

    ok = [0] * n_workers
    shed = [0] * n_workers
    errors = [0] * n_workers
    late = [0] * n_workers
    lat_ms: list[list[float]] = [[] for _ in range(n_workers)]
    t_end = time.perf_counter() + args.duration

    def _fire(
        conn: http.client.HTTPConnection | None, w: int, path: str,
        honor_retry_after: bool,
    ) -> http.client.HTTPConnection | None:
        """One request on a kept-alive connection; returns the connection
        to reuse (None after a transport error)."""
        if conn is None:
            conn = http.client.HTTPConnection(host, port, timeout=60)
        t0 = time.perf_counter()
        try:
            conn.request("GET", path)
            r = conn.getresponse()
            retry_after = r.getheader("Retry-After")
            r.read()
            if r.status == 200:
                ok[w] += 1
                lat_ms[w].append((time.perf_counter() - t0) * 1000)
            elif r.status == 503 and retry_after:
                # the whole fleet shed: honest backpressure
                shed[w] += 1
                if honor_retry_after:
                    time.sleep(min(2.0, float(retry_after)))
            else:
                errors[w] += 1
        except Exception:
            errors[w] += 1
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        return conn

    def worker(w: int) -> None:
        conn: http.client.HTTPConnection | None = None
        j = w
        while time.perf_counter() < t_end:
            path = f"/recommend/u{j % args.users}?howMany={args.how_many}"
            j += n_workers
            conn = _fire(conn, w, path, honor_retry_after=True)
        if conn is not None:
            conn.close()

    open_loop = args.arrival_rate is not None
    if open_loop:
        arrivals = _build_arrivals(args, random.Random(args.seed))
        next_i = [0]
        i_lock = threading.Lock()
        t_base = time.perf_counter() + 0.05  # let all workers spin up

        def open_worker(w: int) -> None:
            # open loop: a worker does NOT honor Retry-After or wait for
            # the fleet to recover — it fires the next scheduled arrival
            # on time. Lateness means the client pool itself saturated
            # (add --workers), not that the fleet slowed us down.
            conn: http.client.HTTPConnection | None = None
            while True:
                with i_lock:
                    i = next_i[0]
                    next_i[0] += 1
                if i >= len(arrivals):
                    break
                offset, path = arrivals[i]
                delay = t_base + offset - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                elif delay < -0.05:
                    late[w] += 1
                conn = _fire(conn, w, path, honor_retry_after=False)
            if conn is not None:
                conn.close()

    t0 = time.perf_counter()
    target = open_worker if open_loop else worker
    threads = [
        threading.Thread(target=target, args=(w,)) for w in range(n_workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0

    lats = sorted(x for ws in lat_ms for x in ws)
    n_ok, n_shed, n_err = sum(ok), sum(shed), sum(errors)
    pct = lambda p: (
        round(lats[min(len(lats) - 1, int(p / 100 * len(lats)))], 2)
        if lats
        else None
    )
    report = {
        "mode": "open" if open_loop else "closed",
        "requests": n_ok,
        "shed_503": n_shed,
        "errors": n_err,
        "seconds": round(dt, 2),
        "qps": round(n_ok / dt, 1) if dt else 0.0,
        "latency_ms": {"p50": pct(50), "p90": pct(90), "p99": pct(99)},
        "workers": n_workers,
        "users": args.users,
        "front": _front_books(host, port),
    }
    if open_loop:
        report["offered"] = {
            "rate": args.arrival_rate,
            "pattern": args.pattern,
            "user_dist": args.user_dist,
            "scheduled": len(arrivals),
            "late": sum(late),
        }
    print(json.dumps(report))
    # contract: behind a healthy front every request is answered or
    # honestly shed — any residual error is a finding
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
