"""Property-based tests (Hypothesis) for the codec and wire-format layers:
any data the framework can be handed must round-trip exactly — the same
contract the reference pins with its TextUtilsTest/ConfigUtils suites,
pushed over the full input space instead of cherry-picked cases."""

import json
import sys

import pytest
from pathlib import Path

# hypothesis is an optional dev dependency: absent in the minimal CI
# container, the whole suite must still COLLECT cleanly (a hard import
# here was a tier-1 collection error, not a skip)
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from oryx_tpu.common.text import (
    from_json,
    join_csv,
    join_delimited,
    parse_csv,
    parse_delimited,
    parse_input_line,
    to_json,
)

# text with no NUL (filesystem/wire-hostile) but full unicode otherwise
texts = st.text(
    alphabet=st.characters(blacklist_categories=("Cs",), blacklist_characters="\x00"),
    max_size=80,
)


@settings(max_examples=200, deadline=None)
@given(st.lists(texts, min_size=1, max_size=8))
def test_csv_roundtrip(values):
    assert parse_csv(join_csv(values)) == values


@settings(max_examples=200, deadline=None)
@given(st.lists(st.text(alphabet=st.characters(
    blacklist_categories=("Cs",), blacklist_characters="\x00,\n\r\""),
    max_size=40), min_size=1, max_size=8))
def test_delimited_roundtrip_without_delimiter_chars(values):
    assert parse_delimited(join_delimited(values)) == values


@settings(max_examples=200, deadline=None)
@given(st.recursive(
    st.none() | st.booleans() | st.integers(-2**53, 2**53) | texts,
    lambda children: st.lists(children, max_size=4)
    | st.dictionaries(texts, children, max_size=4),
    max_leaves=10,
))
def test_json_roundtrip(value):
    assert from_json(to_json(value)) == value


# parse_input_line strips the line first (reference PARSE_FN trims), so
# fields at the line edges must not carry outer whitespace; and a leading
# '[' switches to JSON-array parsing
input_fields = st.text(
    alphabet=st.characters(
        blacklist_categories=("Cs", "Zs", "Zl", "Zp", "Cc"),
        blacklist_characters='\x00,"[',
    ),
    min_size=1,
    max_size=40,
)


@settings(max_examples=200, deadline=None)
@given(st.lists(input_fields, min_size=1, max_size=6))
def test_input_line_csv(values):
    assert parse_input_line(join_csv(values)) == values


def test_input_line_malformed_json_raises_valueerror():
    """A '['-prefixed line that is not valid JSON raises ValueError
    (JSONDecodeError subclasses it), which the layers' poison-message
    isolation already catches — found by the property sweep."""
    import pytest

    with pytest.raises(ValueError):
        parse_input_line("[")


# ---------------------------------------------------------------------------
# file-log wire format: random keys/messages round-trip through a real
# broker file (shared format with the native C++ appender)
# ---------------------------------------------------------------------------


@settings(max_examples=50, deadline=None)
@given(st.lists(
    st.tuples(st.none() | texts, texts), min_size=1, max_size=12,
))
def test_filelog_roundtrip(tmp_path_factory, records):
    from oryx_tpu.bus.filelog import FileLogBroker

    root = tmp_path_factory.mktemp("flog")
    broker = FileLogBroker(str(root))
    broker.create_topic("t", partitions=1)
    for key, msg in records:
        broker.send("t", key, msg, partition=0)
    got = broker.read("t", 0, 0, len(records) + 5)
    assert [(k, m) for _, k, m in got] == records
    broker.close() if hasattr(broker, "close") else None


@settings(max_examples=100, deadline=None)
@given(st.none() | texts, texts)
def test_encode_record_parses_back(key, message):
    import struct

    from oryx_tpu.bus.filelog import encode_record

    rec = encode_record(key, message)
    (klen,) = struct.unpack_from("<i", rec, 0)
    off = 4
    if klen < 0:
        k = None
    else:
        k = rec[off : off + klen].decode("utf-8")
        off += klen
    (mlen,) = struct.unpack_from("<I", rec, off)
    off += 4
    m = rec[off : off + mlen].decode("utf-8")
    assert off + mlen == len(rec)
    assert k == key and m == message


# ---------------------------------------------------------------------------
# kafka magic-v2 record batches: arbitrary bytes round-trip, including the
# CRC32C the wire protocol validates
# ---------------------------------------------------------------------------


@settings(max_examples=100, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.none() | st.binary(max_size=60),
            st.none() | st.binary(max_size=200),
        ),
        min_size=1,
        max_size=10,
    ),
    st.integers(0, 2**40),
)
def test_kafka_record_batch_roundtrip(records, ts):
    from oryx_tpu.bus.kafkawire import decode_record_batches, encode_record_batch

    batch = encode_record_batch(records, base_timestamp_ms=ts)
    got = decode_record_batches(batch)
    assert [(k, v) for _, k, v in got] == records
    assert [o for o, _, _ in got] == list(range(len(records)))


@settings(max_examples=60, deadline=None)
@given(st.binary(min_size=0, max_size=300))
def test_crc32c_matches_known_implementation(data):
    """The table-driven CRC32C must agree with the canonical bit-by-bit
    definition (Castagnoli polynomial, reflected)."""
    from oryx_tpu.bus.kafkawire import _crc32c_py

    def slow_crc32c(b: bytes) -> int:
        crc = 0xFFFFFFFF
        for byte in b:
            crc ^= byte
            for _ in range(8):
                crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
        return crc ^ 0xFFFFFFFF

    assert _crc32c_py(data) == slow_crc32c(data)


# ---------------------------------------------------------------------------
# HOCON-subset config parser: generated documents with known structure
# ---------------------------------------------------------------------------

from oryx_tpu.common.config import Config, parse_config  # noqa: E402


keys = st.from_regex(r"[a-z][a-z0-9-]{0,10}", fullmatch=True)
scalars = st.one_of(
    st.integers(-10**9, 10**9),
    st.booleans(),
    st.none(),
    st.floats(-1e6, 1e6, allow_nan=False).map(lambda f: round(f, 4)),
    st.from_regex(r"[A-Za-z][A-Za-z0-9_./:@#-]{0,20}", fullmatch=True),
)
config_dicts = st.recursive(
    st.dictionaries(keys, scalars, min_size=1, max_size=4),
    lambda children: st.dictionaries(
        keys, scalars | children | st.lists(scalars, max_size=3),
        min_size=1, max_size=4,
    ),
    max_leaves=12,
)


def _render(d, indent=0):
    """Emit a document in the supported syntax from a known dict."""
    out = []
    pad = "  " * indent
    for k, v in d.items():
        if isinstance(v, dict):
            out.append(f"{pad}{k} = {{")
            out.append(_render(v, indent + 1))
            out.append(pad + "}")
        elif isinstance(v, list):
            items = ", ".join(_scalar_text(x) for x in v)
            out.append(f"{pad}{k} = [{items}]")
        else:
            out.append(f"{pad}{k} = {_scalar_text(v)}")
    return "\n".join(out)


def _scalar_text(v):
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return f'"{v}"'
    return repr(v)


def _normalize(v):
    # floats that render as integers (e.g. 2.0 -> "2.0") survive; ints stay
    # ints; everything else round-trips exactly
    if isinstance(v, dict):
        return {k: _normalize(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_normalize(x) for x in v]
    return v


@settings(max_examples=200, deadline=None)
@given(config_dicts)
def test_hocon_parser_roundtrip(doc):
    parsed = parse_config(_render(doc))._data
    assert parsed == _normalize(doc)


@settings(max_examples=100, deadline=None)
@given(config_dicts, config_dicts)
def test_overlay_deep_merges(base, over):
    cfg = parse_config(_render(base)).overlay(
        {  # dotted-path overlay of every leaf of `over`
            k: v
            for k, v in _flatten_paths(over).items()
        }
    )
    for path, v in _flatten_paths(over).items():
        assert cfg.get(path) == v


def _flatten_paths(d, prefix=""):
    out = {}
    for k, v in d.items():
        p = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten_paths(v, p))
        else:
            out[p] = v
    return out


# ---------------------------------------------------------------------------
# cross-IMPLEMENTATION batch roundtrips: the client's codec against the
# transcript tool's independent spec-level implementation, both directions
# and every compression codec — double-entry bookkeeping under fuzzing,
# not just on the golden transcripts
# ---------------------------------------------------------------------------

_rec_lists = st.lists(
    st.tuples(
        st.none() | st.binary(max_size=40),
        st.binary(max_size=150),
    ),
    min_size=1,
    max_size=8,
)


sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import kafka_transcripts as indep  # noqa: E402 - the independent impl


def _codec_available(codec: int) -> bool:
    """lz4/zstd ride system libraries on BOTH sides (the tool's own
    ctypes bindings, the client's bus/compress.py); hosts without them
    skip those draws like the transcript suite does."""
    try:
        from oryx_tpu.bus.kafkawire import decode_record_batches

        decode_record_batches(indep.record_batch(0, [(None, b"x")], codec=codec))
        return True
    except Exception:
        return False


_CODECS = [c for c in (0, 1, 2, 3) if _codec_available(c)]


@settings(max_examples=60, deadline=None)
@given(_rec_lists, st.sampled_from(_CODECS))
def test_independent_batches_decode_in_client(records, codec):
    """Independent encoder (own varints/CRC/codecs, tools/) -> client
    decoder, per codec (none, gzip, snappy, lz4). gzip/snappy exercise
    the tool's own encoders; lz4 its own ctypes binding vs the client's."""
    from oryx_tpu.bus.kafkawire import decode_record_batches

    batch = indep.record_batch(7, records, codec=codec)
    got = decode_record_batches(batch)
    assert [(k, v) for _, k, v in got] == records
    assert [o for o, _, _ in got] == list(range(7, 7 + len(records)))


@settings(max_examples=60, deadline=None)
@given(_rec_lists, st.integers(0, 2**40))
def test_client_batches_decode_in_independent(records, ts):
    """Client encoder -> independent decoder (which VALIDATES the CRC32C
    with its own table): a layout or checksum bug in either half cannot
    cancel out."""
    from oryx_tpu.bus.kafkawire import encode_record_batch

    batch = encode_record_batch(records, base_timestamp_ms=ts)
    got = indep.decode_record_batches_indep(batch)
    assert [(k, v) for _, k, v in got] == records


@pytest.mark.skipif(
    not _codec_available(4), reason="system libzstd unavailable"
)
@settings(max_examples=40, deadline=None)
@given(_rec_lists)
def test_independent_zstd_batches_decode_in_client(records):
    from oryx_tpu.bus.kafkawire import decode_record_batches

    batch = indep.record_batch(0, records, codec=4)  # zstd
    got = decode_record_batches(batch)
    assert [(k, v) for _, k, v in got] == records
