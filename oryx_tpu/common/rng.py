"""Central RNG management with a process-wide deterministic test switch.

Mirrors the reference's RandomManager (framework/oryx-common
.../random/RandomManager.java:37-75): production code asks this module for
generators; tests flip `use_test_seed()` once and every random code path in
the process becomes deterministic.

TPU-native twist: alongside numpy Generators we hand out `jax.random` keys,
split from a managed root key so jitted code is reproducible too.
"""

from __future__ import annotations

import os
import threading

import numpy as np

_TEST_SEED_ENV = "ORYX_TEST_SEED"
_lock = threading.Lock()


class RandomManager:
    _test_seed: int | None = None
    _generators: list[np.random.Generator] = []
    _key_seq: int = 0

    @classmethod
    def use_test_seed(cls, seed: int | None = None) -> None:
        """Switch the whole process to a fixed seed (reference
        RandomManager.useTestSeed, RandomManager.java:60-75). Existing
        generators handed out earlier are re-seeded in place, and the
        allocation sequence restarts so each call site sees the same stream
        regardless of what previous tests allocated."""
        with _lock:
            cls._test_seed = int(
                seed if seed is not None else os.environ.get(_TEST_SEED_ENV, 1234)
            )
            cls._key_seq = 0
            for i, g in enumerate(cls._generators):
                g.bit_generator.state = np.random.PCG64(cls._test_seed + i).state
            cls._generators = []

    @classmethod
    def clear_test_seed(cls) -> None:
        with _lock:
            cls._test_seed = None

    @classmethod
    def get_random(cls) -> np.random.Generator:
        """A numpy Generator; fixed-seeded iff in test mode. Generators are
        only recorded in test mode (for re-seeding) — a long-running
        production process must not accumulate every generator ever made."""
        with _lock:
            if cls._test_seed is None:
                return np.random.default_rng()
            g = np.random.default_rng(cls._test_seed + len(cls._generators))
            cls._generators.append(g)
            return g

    @classmethod
    def get_key(cls):
        """A fresh jax.random key, deterministic under the test seed."""
        import jax

        with _lock:
            if cls._test_seed is not None:
                seed = cls._test_seed + cls._key_seq
            else:
                seed = int.from_bytes(os.urandom(4), "little")
            cls._key_seq += 1
        return jax.random.key(seed)
