"""Pod launcher smoke (round-2 verdict #7): `cli.py pod` brings up a
multi-process deployment through the CLI path — the analogue of the
reference's oryx-run.sh spark-submit/YARN assembly
(deploy/bin/oryx-run.sh:199-235), with the cluster plane replaced by a
jax.distributed process group.

Topology under test, all on one machine over a file:// broker (the
2-host pattern from tests/test_multihost.py through the CLI instead of
raw worker scripts): 2 compute (batch) processes joined into one Gloo
process group + 1 serving process. Asserts: both members join the group
(process 0/2 AND 1/2 markers), input flows through a batch generation to
a MODEL on the update topic, ONLY the leader publishes (non-leaders use
the null producer), serving picks the model up and answers, and SIGTERM
tears the whole pod down cleanly.
"""

from __future__ import annotations

import json
import pathlib
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from oryx_tpu.common.ioutil import choose_free_port

REPO = str(pathlib.Path(__file__).resolve().parent.parent)


def _http(url, timeout=5):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.mark.slow
def test_pod_two_compute_plus_serving_e2e(tmp_path):
    bus = f"file://{tmp_path}/bus"
    port = choose_free_port()
    sets = [
        "oryx.id=pod",
        f"oryx.input-topic.broker={bus}",
        f"oryx.update-topic.broker={bus}",
        f"oryx.batch.storage.data-dir={tmp_path}/data",
        f"oryx.batch.storage.model-dir={tmp_path}/model",
        "oryx.batch.streaming.generation-interval-sec=2",
        "oryx.batch.update-class=oryx_tpu.apps.example.batch.ExampleBatchLayerUpdate",
        f"oryx.serving.api.port={port}",
        "oryx.serving.model-manager-class=oryx_tpu.apps.example.serving.ExampleServingModelManager",
        'oryx.serving.application-resources=["oryx_tpu.serving.resources.common","oryx_tpu.serving.resources.example"]',
    ]
    flat = [x for kv in sets for x in ("--set", kv)]

    r = subprocess.run(
        [sys.executable, "-m", "oryx_tpu.cli", "setup", *flat],
        cwd=REPO, capture_output=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr.decode()[-500:]

    out_path = tmp_path / "pod.out"
    err_path = tmp_path / "pod.err"
    # files, not pipes: three children's logs over a minute would fill a
    # 64KB pipe buffer and deadlock the pod against this test
    pod = subprocess.Popen(
        [
            sys.executable, "-m", "oryx_tpu.cli", "pod",
            "--compute", "2", "--serving", *flat,
        ],
        cwd=REPO,
        stdout=open(out_path, "wb"),
        stderr=open(err_path, "wb"),
        start_new_session=True,
    )
    try:
        # serving up (model-independent endpoint)
        deadline = time.time() + 90
        while time.time() < deadline:
            if pod.poll() is not None:
                raise AssertionError(
                    f"pod died rc={pod.returncode}: "
                    + err_path.read_text(errors="replace")[-2000:]
                )
            try:
                status, _ = _http(f"http://127.0.0.1:{port}/metrics")
                if status == 200:
                    break
            except Exception:
                time.sleep(0.3)
        else:
            raise AssertionError("serving never came up")

        # feed input through the CLI input path
        r = subprocess.run(
            [sys.executable, "-m", "oryx_tpu.cli", "input", *flat],
            cwd=REPO,
            input=b"the quick brown fox\nthe lazy dog\nthe end\n",
            capture_output=True,
            timeout=60,
        )
        assert r.returncode == 0, r.stderr.decode()[-500:]

        # a MODEL lands on the update topic (leader-published)
        from oryx_tpu.bus.broker import get_broker

        broker = get_broker(bus)
        deadline = time.time() + 120
        model_msgs = []
        while time.time() < deadline and not model_msgs:
            msgs = []
            for p in range(broker.num_partitions("OryxUpdate")):
                msgs += broker.read("OryxUpdate", p, 0, 1000)
            model_msgs = [m for m in msgs if m[1] == "MODEL"]
            time.sleep(0.5)
        assert model_msgs, "no MODEL published by the pod's batch tier"

        # serving consumed it and answers a model endpoint
        deadline = time.time() + 60
        ok = False
        while time.time() < deadline and not ok:
            status, body = _http(f"http://127.0.0.1:{port}/distinct")
            if status == 200 and json.loads(body).get("the", 0) >= 3:
                ok = True
            else:
                time.sleep(0.5)
        assert ok, "serving never served the pod-built model"

        # graceful teardown: SIGTERM the launcher, whole pod exits clean
        pod.send_signal(signal.SIGTERM)
        pod.wait(timeout=30)
        err = err_path.read_text(errors="replace")
        # both members joined the jax.distributed group via the CLI path
        assert "joined JAX process group: process 0/2" in err, err[-2000:]
        assert "joined JAX process group: process 1/2" in err, err[-2000:]
        assert pod.returncode == 0, (pod.returncode, err[-1000:])
    finally:
        if pod.poll() is None:
            pod.kill()
            pod.wait()


def test_pod_child_flags_keeps_pod_valued_flags():
    """The argv rebuild must drop only the SUBCOMMAND token 'pod' and the
    pod-only flags — a legitimate flag value spelled 'pod' (e.g.
    --conf pod, or --set oryx.id=pod tokenized oddly) survives
    (round-3 advice)."""
    from oryx_tpu.cli import _pod_child_flags

    argv = [
        "pod", "--conf", "pod", "--compute", "4", "--coordinator",
        "h:1", "--set", "oryx.id=pod", "--serving",
    ]
    assert _pod_child_flags(argv) == [
        "--conf", "pod", "--set", "oryx.id=pod",
    ]
    # '=' forms of pod flags are dropped whole
    assert _pod_child_flags(["pod", "--compute=8", "--conf", "x.conf"]) == [
        "--conf", "x.conf",
    ]
    # options BEFORE the positional (argparse allows it): a flag value
    # spelled 'pod' must not be mistaken for the subcommand token
    # (round-4 advice)
    assert _pod_child_flags(["--conf", "pod", "pod", "--compute", "2"]) == [
        "--conf", "pod",
    ]
    assert _pod_child_flags(["--conf=pod", "pod", "--serving"]) == ["--conf=pod"]
