"""oryx_tpu — a TPU-native lambda-architecture ML framework.

A from-scratch realization of streaming lambda-architecture machine learning
(batch model builds + incremental speed-layer updates + low-latency serving)
with the compute tier on JAX/XLA/pjit over TPU device meshes instead of
Spark/MLlib on YARN, and a native message-log bus in place of Kafka.

Layer map (mirrors the reference framework's capabilities, re-designed TPU-first;
see SURVEY.md for the reference inventory):

  oryx_tpu.common    config / rng / text / io / exec / artifact utilities
  oryx_tpu.bus       message-log backend (topics, offsets, replay) + native broker
  oryx_tpu.ops       JAX math tier: vector ops, solvers, ALS/k-means/RDF kernels
  oryx_tpu.parallel  device mesh + sharding helpers (pjit/shard_map collectives)
  oryx_tpu.ml        batch ML harness: hyperparam search, eval, generation loop
  oryx_tpu.layers    batch + speed layer runtimes
  oryx_tpu.serving   REST serving layer with in-device models
  oryx_tpu.apps      packaged applications: ALS, k-means, random decision forest
  oryx_tpu.api       user-facing SPI (batch update / speed + serving model managers)
"""

__version__ = "0.1.0"
