"""Wordcount example REST endpoints — parity with app/example .../serving/
{Distinct,Add}.java:

  GET  /distinct            -> the whole word -> count map
  GET  /distinct/{word}     -> one word's count (400 if absent)
  POST /add  (or /add/{line}) -> send lines to the input topic
"""

from __future__ import annotations

from oryx_tpu.serving.app import OryxServingException, Request, ServingApp
from oryx_tpu.serving.resources.common import send_input_lines


def register(app: ServingApp) -> None:
    @app.route("GET", "/distinct")
    def distinct(a: ServingApp, req: Request):
        return a.get_serving_model().get_words()

    @app.route("GET", "/distinct/{word}")
    def distinct_word(a: ServingApp, req: Request):
        count = a.get_serving_model().get_count(req.params["word"])
        if count is None:
            raise OryxServingException(400, "No such word")
        return count

    @app.route("POST", "/add/{line}")
    def add_one(a: ServingApp, req: Request):
        a.send_input(req.params["line"])
        return 200, None

    @app.route("POST", "/add")
    def add(a: ServingApp, req: Request):
        # unlike /ingest, an empty flush has always been a 200 no-op here
        send_input_lines(a, req.body_text(), "lines", required=False)
        return 200, None

    def _example_console(a: ServingApp) -> list[tuple[str, object]]:
        model = a.get_serving_model()
        words = model.get_words()
        return [("distinct words", len(words))]

    app.console_sections.append(("Word count model", _example_console))
