"""Zero-dependency utility base: config, RNG, text, IO, concurrency, artifacts.

TPU-native equivalent of the reference's framework/oryx-common
(ConfigUtils.java, RandomManager.java, TextUtils.java, ExecUtils.java,
IOUtils.java, ClassUtils.java, PMMLUtils.java).
"""

from oryx_tpu.common.config import Config, ConfigError, load_config, default_config
from oryx_tpu.common.rng import RandomManager
