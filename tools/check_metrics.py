#!/usr/bin/env python
"""Static metric-name consistency check (wired as a tier-1 test).

Three invariants, so metric docs and the bench ratchet cannot drift from
the code:

1. Every metric name used under ``oryx_tpu/`` (any string literal that is
   exactly an ``oryx_``-prefixed identifier) matches the naming contract
   ``^oryx_[a-z0-9_]+$``.
2. Every such name appears in the reference table of
   ``docs/observability.md`` (a row whose first column is the backticked
   name) — and every name in the table exists in code.
3. Every metric name ratcheted in ``BASELINE_RATCHET.json``
   (tools/check_bench.py) still exists in ``bench.py``'s output
   vocabulary — a renamed bench field would otherwise make the ratchet
   fail every future run as "missing" (or, worse, silently skip on a
   platform filter) long after the measurement it locks moved on.

Histogram series suffixes (``_bucket``/``_sum``/``_count``) are derived by
the exposition layer and are documented under the base name only.

Exit status 0 = consistent; 1 = drift (each problem printed on stderr).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "oryx_tpu"
DOC = ROOT / "docs" / "observability.md"
BENCH = ROOT / "bench.py"
RATCHET = ROOT / "BASELINE_RATCHET.json"

VALID_NAME = re.compile(r"^oryx_[a-z0-9_]+$")
# A whole string literal that is an oryx_-prefixed identifier. Literals
# with any other characters (spaces, braces, dots) are scrape patterns or
# prose, not metric registrations, and are skipped on purpose.
CODE_LITERAL = re.compile(r"""["'](oryx_[A-Za-z0-9_]+)["']""")
# A reference-table row whose first cell is the backticked metric name.
DOC_ROW = re.compile(r"^\|\s*`(oryx_[^`]+)`", re.M)

# Not metrics: the package's own name appears as a string in a few places.
IGNORE = {"oryx_tpu"}

# Score-mode vocabulary (PR 8): bench fields the serving-mode claims ride
# on, and the label key the batcher's dispatch records carry. A rename in
# bench.py or docs would otherwise silently orphan the recall gate's and
# the per-mode dashboards' names.
REQUIRED_BENCH_FIELDS = (
    "qps_quantized",
    "approx_recall_at_10",
    "quantized_recall_at_10",
    "lsh_measured_recall_at_10",
)
REQUIRED_DOC_TOKENS = ("score_mode",)


def vocabulary_problems() -> list[str]:
    problems = []
    bench_text = BENCH.read_text(encoding="utf-8")
    for name in REQUIRED_BENCH_FIELDS:
        if not re.search(rf'"{re.escape(name)}"', bench_text):
            problems.append(
                f"{name}: required bench vocabulary missing from bench.py"
            )
    doc_text = DOC.read_text(encoding="utf-8")
    for tok in REQUIRED_DOC_TOKENS:
        if tok not in doc_text:
            problems.append(
                f"{tok}: required label name missing from docs/observability.md"
            )
    return problems


def code_metric_names() -> dict[str, str]:
    """name -> first file using it, for every metric-shaped literal."""
    names: dict[str, str] = {}
    for py in sorted(PACKAGE.rglob("*.py")):
        text = py.read_text(encoding="utf-8")
        for m in CODE_LITERAL.finditer(text):
            name = m.group(1)
            if name not in IGNORE:
                names.setdefault(name, str(py.relative_to(ROOT)))
    return names


def doc_metric_names() -> set[str]:
    return set(DOC_ROW.findall(DOC.read_text(encoding="utf-8")))


def ratchet_problems() -> list[str]:
    """Every ratcheted metric name must appear as a quoted key literal in
    bench.py — the static stand-in for 'bench.py output emits it'."""
    if not RATCHET.exists():
        return [f"missing {RATCHET.relative_to(ROOT)}"]
    import json

    try:
        metrics = json.loads(RATCHET.read_text(encoding="utf-8"))["metrics"]
    except (json.JSONDecodeError, KeyError, TypeError) as e:
        return [f"{RATCHET.name}: unparseable ({e})"]
    bench_text = BENCH.read_text(encoding="utf-8")
    problems = []
    for m in metrics:
        name = m.get("name")
        if not name:
            problems.append(f"{RATCHET.name}: metric entry without a name: {m}")
        elif not re.search(rf'"{re.escape(name)}"', bench_text):
            problems.append(
                f"{name}: ratcheted in {RATCHET.name} but bench.py never "
                "emits a field of that name — the ratchet would fail every "
                "run as 'missing'"
            )
    return problems


def main() -> int:
    problems: list[str] = []
    if not DOC.exists():
        print(f"missing {DOC.relative_to(ROOT)}", file=sys.stderr)
        return 1
    code = code_metric_names()
    doc = doc_metric_names()
    for name in sorted(code):
        where = code[name]
        if not VALID_NAME.match(name):
            problems.append(
                f"{name} ({where}): does not match ^oryx_[a-z0-9_]+$"
            )
        elif name not in doc:
            problems.append(
                f"{name} ({where}): missing from the docs/observability.md "
                "metric reference table"
            )
    for name in sorted(doc - set(code)):
        problems.append(
            f"{name}: documented in docs/observability.md but not found "
            "anywhere under oryx_tpu/"
        )
    problems.extend(ratchet_problems())
    problems.extend(vocabulary_problems())
    for p in problems:
        print(p, file=sys.stderr)
    if not problems:
        print(f"ok: {len(code)} metric names consistent with docs")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
