"""oryxlint CLI: ``python -m tools.oryxlint [--changed] [--json]``.

Exit status 0 = clean, 1 = findings (each printed as file:line: [rule]
message), 2 = usage/internal error. ``--changed`` scopes per-file rules
to files touched per git (staged, unstaged, and untracked) for fast
pre-commit runs; whole-tree consistency rules always run.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.oryxlint.core import known_rules, run_lint  # noqa: E402


def _changed_files(root: str) -> set[str]:
    """Repo-relative paths touched per git status (staged + unstaged +
    untracked). Falls back to the empty set outside a work tree."""
    try:
        proc = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return set()
    if proc.returncode != 0:
        return set()
    out: set[str] = set()
    for ln in proc.stdout.splitlines():
        if len(ln) < 4:
            continue
        path = ln[3:].strip()
        if " -> " in path:  # rename: take the new side
            path = path.split(" -> ", 1)[1]
        out.add(path.strip('"'))
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="oryxlint", description=__doc__.splitlines()[0]
    )
    ap.add_argument(
        "--root", default=ROOT, help="repo root to lint (default: this repo)"
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="scope per-file rules to git-changed files (fast pre-commit)",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable output: {findings, suppressed, rules}; each "
        "finding carries stable path/line/rule/severity/fix_hint/message "
        "fields (consumed by tools/precommit.sh)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    ap.add_argument(
        "--stats", action="store_true",
        help="print call-graph resolution-rate stats instead of linting",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in sorted(known_rules().items()):
            print(f"{rid}: {desc}")
        return 0

    if args.stats:
        from tools.oryxlint.callgraph import ProjectIndex, body_calls
        from tools.oryxlint.core import Project

        project = Project.load(args.root)
        idx = ProjectIndex(project)
        for fi in idx.functions:
            for call in body_calls(fi.node):
                idx.resolve_call(fi, call)
        s = idx.stats
        rate = 100.0 * s["resolved"] / max(1, s["call_sites"])
        print(
            f"oryxlint --stats: resolved {s['resolved']}/{s['call_sites']} "
            f"call sites ({rate:.1f}%), {s['lambda_sites']} lambda call "
            f"site(s) (unresolved), {len(idx.functions)} functions, "
            f"{len(idx.partial_aliases)} partial alias(es)"
        )
        return 0

    changed = _changed_files(args.root) if args.changed else None
    if changed is not None and not changed:
        # stderr so --json stdout stays parseable for the pre-commit hook
        print(
            "oryxlint --changed: no modified files; per-file rules skipped",
            file=sys.stderr,
        )
    active, suppressed = run_lint(args.root, changed=changed)

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in active],
            "suppressed": [f.as_dict() for f in suppressed],
            "rules": known_rules(),
        }, indent=2))
        return 1 if active else 0

    for f in active:
        print(f.render(), file=sys.stderr)
    if active:
        print(
            f"\noryxlint: {len(active)} finding(s) "
            f"({len(suppressed)} suppressed)", file=sys.stderr,
        )
        return 1
    print(f"oryxlint: clean ({len(suppressed)} suppressed finding(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
