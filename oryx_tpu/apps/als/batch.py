"""ALS batch tier: the full TPU model rebuild per generation.

Replaces the reference's Spark-MLlib pipeline (app/oryx-app-mllib
.../als/ALSUpdate.java): parse events, aggregate with decay/delete
semantics, train pjit ALS, evaluate (implicit: mean per-user AUC; explicit:
negative RMSE), publish a *skeleton* artifact (hyperparams + expected ID
lists, no tensors — factor matrices are streamed row-by-row as UP messages
through publish_additional_model_data, the reference's
EnqueueFeatureVecsFn pattern at ALSUpdate.java:286-318), and split
train/test by time instead of randomly (ALSUpdate.java:325-342).
"""

from __future__ import annotations

import logging
import pathlib
import time
from typing import Any, Sequence

import numpy as np

from oryx_tpu.bus.api import KeyMessage, TopicProducer
from oryx_tpu.common.artifact import ModelArtifact
from oryx_tpu.common.config import Config
from oryx_tpu.ml.evaluate import auc_mean_per_user, rmse
from oryx_tpu.ml.update import MLUpdate
from oryx_tpu.ops.als import aggregate_interactions, train_als
from oryx_tpu.apps.als.common import (
    ALSConfig,
    parse_events,
    batch_update_messages,
)

log = logging.getLogger(__name__)


class ALSUpdate(MLUpdate):
    def __init__(self, config: Config, mesh=None):
        super().__init__(config)
        self.als = ALSConfig.from_config(config)
        if mesh is None:
            from oryx_tpu.parallel.distributed import mesh_from_config

            mesh = mesh_from_config(config)
        self.mesh = mesh

    def hyperparam_ranges(self) -> dict[str, Any]:
        return {
            "features": self.als.features,
            "lambda": self.als.lam,
            "alpha": self.als.alpha,
        }

    def split_train_test(self, data: Sequence[KeyMessage]):
        """Temporal split: newest test-fraction of events held out
        (ALSUpdate.java:325-342 sorts by timestamp). Timestamps are read
        per-line in place (unparseable lines get -1 and stay in train) so
        indices always align with `data` even when lines are skipped."""
        if self.test_fraction <= 0 or len(data) == 0:
            return data, []
        from oryx_tpu.common.text import parse_input_line

        ts = np.full(len(data), -1, dtype=np.int64)
        for j, km in enumerate(data):
            try:
                tok = parse_input_line(km.message)
                if len(tok) > 3 and tok[3] != "":
                    ts[j] = int(float(tok[3]))
            except (ValueError, IndexError):
                pass
        valid = ts[ts >= 0]
        if len(valid) == 0 or np.all(valid == valid[0]):
            return super().split_train_test(data)
        order = np.argsort(ts, kind="stable")
        n_test = int(len(data) * self.test_fraction)
        if n_test == 0:
            return data, []
        test_set = set(order[-n_test:].tolist())
        train = [d for j, d in enumerate(data) if j not in test_set]
        test = [d for j, d in enumerate(data) if j in test_set]
        return train, test

    def _aggregate(self, data: Sequence[KeyMessage]):
        users, items, vals, tss = parse_events(data)
        if len(vals) == 0:
            raise ValueError("no parseable interactions")
        return aggregate_interactions(
            users, items, vals, tss,
            implicit=self.als.implicit,
            decay_factor=self.als.decay_factor,
            zero_threshold=self.als.zero_threshold,
            now_ms=int(time.time() * 1000),
            log_strength=self.als.log_strength,
            epsilon=self.als.epsilon,
        )

    def build_model(self, train: Sequence[KeyMessage], hyperparams: dict[str, Any]) -> ModelArtifact:
        agg = self._aggregate(train)
        kwargs = dict(
            features=int(hyperparams["features"]),
            lam=float(hyperparams["lambda"]),
            alpha=float(hyperparams["alpha"]),
            iterations=self.als.iterations,
            implicit=self.als.implicit,
            mesh=self._build_mesh(),
            compute_dtype=self.als.compute_dtype,
        )
        model_dir = self.config.get_string("oryx.batch.storage.model-dir", None)
        if self.als.checkpoint_interval > 0 and model_dir:
            # long builds survive preemption: resume from the last
            # checkpointed sweep instead of restarting the generation.
            # One subdir per hyperparam combo — candidates may build in
            # parallel (oryx.ml.eval.parallelism) and must not share a
            # checkpoint file
            import hashlib
            import json as _json

            from oryx_tpu.common.ioutil import strip_scheme
            from oryx_tpu.ops.als import train_als_checkpointed

            combo = hashlib.sha1(
                _json.dumps(hyperparams, sort_keys=True, default=str).encode()
            ).hexdigest()[:12]
            m = train_als_checkpointed(
                agg,
                pathlib.Path(strip_scheme(model_dir)) / ".als-checkpoint" / combo,
                self.als.checkpoint_interval,
                **kwargs,
            )
        else:
            m = train_als(agg, **kwargs)
        art = ModelArtifact(
            "als",
            extensions={
                "features": str(int(hyperparams["features"])),
                "lambda": str(float(hyperparams["lambda"])),
                "alpha": str(float(hyperparams["alpha"])),
                "implicit": str(self.als.implicit).lower(),
                "logStrength": str(self.als.log_strength).lower(),
            },
            tensors={"X": m.x, "Y": m.y},
        )
        art.set_extension("XIDs", m.user_ids)
        art.set_extension("YIDs", m.item_ids)
        # knownItems per user ride with the X rows at publish time.
        # Vectorized grouping: a per-pair Python dict loop costs ~20s at
        # the 25M-interaction benchmark scale (measured 3x slower than
        # this sort-and-slice form)
        if not self.als.no_known_items and len(agg.users):
            item_arr = np.asarray(agg.item_ids, dtype=object)
            order = np.argsort(agg.users, kind="stable")
            us = agg.users[order]
            its = item_arr[agg.items[order]]
            cut = np.nonzero(np.r_[True, us[1:] != us[:-1]])[0]
            ends = np.r_[cut[1:], len(us)]
            art.content["knownItems"] = {
                agg.user_ids[us[c]]: its[c:e].tolist()
                for c, e in zip(cut, ends)
            }
        return art

    def evaluate(self, model: ModelArtifact, train, test) -> float:
        users, items, vals, _ = parse_events(test)
        if len(vals) == 0:
            return float("nan")
        xids = model.get_extension_list("XIDs")
        yids = model.get_extension_list("YIDs")
        umap = {u: j for j, u in enumerate(xids)}
        imap = {i: j for j, i in enumerate(yids)}
        keep = [
            (umap[u], imap[i], v)
            for u, i, v in zip(users, items, vals)
            if u in umap and i in imap and not np.isnan(v)
        ]
        if not keep:
            return float("nan")
        tu = np.asarray([a for a, _, _ in keep])
        ti = np.asarray([b for _, b, _ in keep])
        tv = np.asarray([c for _, _, c in keep])
        x, y = model.tensors["X"], model.tensors["Y"]
        if self.als.implicit:
            known = {
                umap[u]: {imap[i] for i in its if i in imap}
                for u, its in model.content.get("knownItems", {}).items()
                if u in umap
            }
            return auc_mean_per_user(x, y, tu, ti, known)
        return -rmse(x, y, tu, ti, tv)

    def publish_model(self, model: ModelArtifact, model_path: str, producer: TopicProducer) -> None:
        """Publish a tensor-free skeleton; factor rows stream separately
        (the reference's skeleton-PMML-with-extensions pattern). An
        oversized skeleton ships its bytes as bus chunks ahead of the
        MODEL-REF so other hosts resolve it with no shared mount."""
        from oryx_tpu.common.artifact import publish_model_ref

        skeleton = ModelArtifact("als", dict(model.extensions), {})
        serialized = skeleton.to_string()
        if len(serialized.encode("utf-8")) <= self.max_message_size:
            producer.send("MODEL", serialized)
        else:
            publish_model_ref(
                producer, serialized, model_path, self.max_message_size,
                transfer=self.artifact_transfer,
            )

    def publish_additional_model_data(
        self, model: ModelArtifact, model_path: str, producer: TopicProducer
    ) -> None:
        """Stream every Y row then every X row as UP messages
        (ALSUpdate.java:286-318: Y first so user solves see item vectors)."""
        xids = model.get_extension_list("XIDs")
        yids = model.get_extension_list("YIDs")
        x, y = model.tensors["X"], model.tensors["Y"]
        known = model.content.get("knownItems", {})

        def chunks(kind, ids, mat, known_of=None):
            # batched message building (one C-encoder pass per chunk), in
            # bounded chunks so a million-row flood never materializes one
            # multi-hundred-MB JSON blob
            step = 8192
            dropped = 0
            for lo in range(0, len(ids), step):
                part = ids[lo : lo + step]
                block = mat[lo : lo + len(part)]
                finite = np.isfinite(block).all(axis=1)
                if not finite.all():  # builder contract: NaN is not JSON
                    dropped += int((~finite).sum())
                    rows = np.nonzero(finite)[0]
                    part = [part[j] for j in rows]
                    block = block[rows]
                yield from batch_update_messages(
                    kind, part, block,
                    known_lists=(
                        [known_of.get(i, []) for i in part]
                        if known_of is not None else None
                    ),
                )
            if dropped:
                log.warning("dropped %d non-finite %s factor rows at publish", dropped, kind)

        producer.send_batch(chunks("Y", yids, y))
        producer.send_batch(chunks("X", xids, x, known))
        log.info("published %d Y and %d X factor rows", len(yids), len(xids))
