"""Shared update-topic vector codec — extracted from apps/als/common.py
(ROADMAP item 4's SPI split) so every packaged app that streams factor
rows as ``UP`` messages shares ONE wire format and ONE batched builder.

Payloads are JSON arrays ``[kind, id, [vector]]`` or
``[kind, id, [vector], [known...]]`` — the reference's
ALSSpeedModelManager/ALSUpdate payload shape with the first element
generalized: ALS uses kinds "X"/"Y", the seq app uses "E" for item
embeddings. Byte parity with the historical ALS payloads is pinned by
tests/test_als_state.py::test_batch_update_messages_byte_parity.
"""

from __future__ import annotations

import json

import numpy as np

# UP-message float precision, shared by the single-message and batched
# builders so their payloads stay byte-identical.
ROUND_DECIMALS = 6


def round_vector(vector) -> list:
    # vectorized: a per-element Python round() dominates UP-message cost
    # at speed-tier rates
    return np.round(np.asarray(vector, dtype=np.float64), ROUND_DECIMALS).tolist()


def vector_update_message(
    kind: str, ident: str, vector, known=None
) -> tuple[str, str]:
    """One UP message: ``[kind, id, [vector]]`` (+ sorted known list)."""
    payload = [kind, ident, round_vector(vector)]
    if known is not None:
        payload.append(sorted(known))
    return "UP", json.dumps(payload, separators=(",", ":"))


def batch_update_messages(
    kind: str, ids, vectors, known_lists=None
) -> list[tuple[str, str]]:
    """Batch of UP messages, byte-identical to the single-message path:
    ONE json.dumps serializes the whole [N,K] rounded block through the C
    encoder, and the blob splits on "],[" into per-row number strings
    (rows contain only numbers and commas, so the separator is
    unambiguous). Per-message dumps of the vector floats — 120k Python
    encoder invocations per 20k-event micro-batch — was ~45% of speed-tier
    build time. Callers must pre-filter non-finite rows (NaN/Infinity are
    not valid JSON)."""
    n = len(ids)
    if n == 0:
        return []
    vecs = np.round(np.asarray(vectors, dtype=np.float64), ROUND_DECIMALS)
    blob = json.dumps(vecs.tolist(), separators=(",", ":"))
    rows = blob[2:-2].split("],[")
    assert len(rows) == n
    out = []
    for j, ident in enumerate(ids):
        if known_lists is not None:
            out.append((
                "UP",
                f'["{kind}",{json.dumps(ident)},[{rows[j]}],'
                f'{json.dumps(sorted(known_lists[j]), separators=(",", ":"))}]',
            ))
        else:
            out.append((
                "UP", f'["{kind}",{json.dumps(ident)},[{rows[j]}]]',
            ))
    return out


def parse_update_message(message: str):
    """-> (kind, id, np float32 vector, known_ids list)."""
    arr = json.loads(message)
    kind, ident, vec = arr[0], str(arr[1]), np.asarray(arr[2], dtype=np.float32)
    known = [str(k) for k in arr[3]] if len(arr) > 3 and arr[3] else []
    return kind, ident, vec, known
