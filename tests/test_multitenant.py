"""Multi-tenant batch: concurrent model builds sharing one device
(BASELINE.json configs[4] — "ALS + RDF concurrent model-builds").

The reference runs tenants as separate Spark jobs on a shared YARN
cluster; here tenants share the XLA device. Two builds racing through
jit/compile/execute from different threads must both come out correct —
no cross-talk through the compilation cache, the RNG manager, or the
device — and a serving snapshot taken mid-build must stay consistent.
"""

from __future__ import annotations

import threading

import numpy as np


def test_concurrent_als_and_rdf_builds():
    from oryx_tpu.ml.quality import build_and_evaluate
    from oryx_tpu.ops.rdf import bin_dataset, grow_forest, predict_class_probs

    results: dict = {}
    errors: list = []

    def als_tenant():
        try:
            rep = build_and_evaluate(
                n_users=1500, n_items=900, nnz=80_000, features=16,
                iterations=4, compute_dtype="bfloat16", seed=5,
                sample_users=300,
            )
            results["als"] = rep
        except Exception as e:  # noqa: BLE001
            errors.append(("als", e))

    def rdf_tenant():
        try:
            rng = np.random.default_rng(13)
            X = rng.standard_normal((20_000, 12)).astype(np.float32)
            y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int32)
            binned = bin_dataset(
                X,
                is_categorical=np.zeros(12, dtype=bool),
                category_counts=np.zeros(12, dtype=np.int32),
                max_split_candidates=16,
            )
            forest = grow_forest(
                binned, y, num_trees=6, max_depth=6,
                impurity="entropy", n_classes=2,
            )
            pred = predict_class_probs(forest, binned.binned)
            acc = float((np.asarray(pred).argmax(-1) == y).mean())
            results["rdf_acc"] = acc
        except Exception as e:  # noqa: BLE001
            errors.append(("rdf", e))

    threads = [
        threading.Thread(target=als_tenant, name="tenant-als"),
        threading.Thread(target=rdf_tenant, name="tenant-rdf"),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    assert results["als"].nan_rows == 0
    assert results["als"].auc > 0.70
    assert results["rdf_acc"] > 0.85
