"""Analytic FLOP accounting and chip-peak lookup for MFU reporting.

The reference publishes qps/latency tables but no utilization measure
(docs/docs/performance.html); on TPU the honest perf bar is MFU —
achieved FLOP/s over the chip's dense peak — because it distinguishes
"fast" from "underutilized" (a serving kernel can beat a 437-qps CPU
baseline a hundredfold while using 2% of the MXU). The FLOP counts here
are analytic lower bounds over the dominant matmul/einsum terms only
(top-k selection, masking and solves are excluded unless noted), so the
reported MFU slightly understates true utilization — never the reverse.
"""

from __future__ import annotations

# Dense per-chip matmul peak in FLOP/s at bf16, from public spec sheets
# (cloud.google.com/tpu/docs/system-architecture-tpu-vm). The f32 figure
# is taken as half the bf16 peak — the convention for chips that run f32
# matmuls as multi-pass bf16 on the MXU.
_PEAK_BF16 = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 394e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

# Dense int8 OP/s peaks. v5e/v5p/v6e run int8 at 2x the bf16 rate on the
# MXU; earlier generations have no int8 fast path and score int8 operands
# at the bf16 rate after conversion. An MFU for a quantized dispatch must
# divide by THIS peak — dividing int8 throughput by the bf16 peak would
# flatter a quantized kernel by up to 2x on chips with int8 support.
_PEAK_INT8 = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 788e12,
    "v5p": 918e12,
    "v6e": 1836e12,
}


def normalize_dtype(dtype: str) -> str:
    """Canonical dtype tag for peak lookup: int8 / bfloat16 / float32.
    Unrecognized tags conservatively map to bfloat16 (the serving
    default), never to the higher int8 peak."""
    d = str(dtype).lower()
    if d in ("int8", "i8", "s8"):
        return "int8"
    if d in ("float32", "f32"):
        return "float32"
    return "bfloat16"


def peak_flops_for_kind(device_kind: str, dtype: str = "bfloat16") -> float | None:
    """Per-chip dense peak FLOP/s for a jax device_kind string at the
    dtype actually dispatched (int8 / bfloat16 / float32), or None when
    the chip generation can't be identified (MFU is then omitted rather
    than guessed)."""
    kind = device_kind.lower()
    if "v6" in kind or "trillium" in kind:
        gen = "v6e"
    elif "v5p" in kind:
        gen = "v5p"
    elif "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
        gen = "v5e"
    elif "v5" in kind:
        gen = "v5p"
    elif "v4" in kind:
        gen = "v4"
    elif "v3" in kind:
        gen = "v3"
    elif "v2" in kind:
        gen = "v2"
    else:
        return None
    d = normalize_dtype(dtype)
    if d == "int8":
        return _PEAK_INT8[gen]
    peak = _PEAK_BF16[gen]
    if d == "float32":
        peak /= 2
    return peak


def device_peak_flops(dtype: str = "bfloat16") -> float | None:
    """Peak FLOP/s of jax's default device; None off-TPU (no honest CPU
    peak is derivable from here) or for unknown TPU generations."""
    import jax

    d = jax.devices()[0]
    if d.platform != "tpu":
        return None
    return peak_flops_for_kind(getattr(d, "device_kind", "") or "", dtype)


def topk_score_flops(n_queries: int, n_items: int, features: int) -> float:
    """FLOPs for exact top-k scoring: one [B,F]x[F,I] matmul = 2·B·I·F
    (selection excluded)."""
    return 2.0 * n_queries * n_items * features


def als_halfstep_flops(n_rows: int, pad_width: int, k: int, n_fixed: int) -> float:
    """Analytic FLOPs for one ALS half-sweep over n_rows padded lists of
    width pad_width against k features (ops/als.py _half_step): the
    normal-equation einsum 2·B·P·K² + the RHS einsum 2·B·P·K, plus the
    fixed side's gram 2·M·K². Cholesky/solves (O(B·K³/3)) excluded."""
    return (
        2.0 * n_rows * pad_width * k * k
        + 2.0 * n_rows * pad_width * k
        + 2.0 * n_fixed * k * k
    )


def mfu(achieved_flops_per_s: float, peak: float | None) -> float | None:
    """Model FLOPs Utilization in [0,1], or None when no peak is known."""
    if not peak or peak <= 0:
        return None
    return achieved_flops_per_s / peak
