"""Test harness bootstrap.

Mirrors the reference's test strategy (SURVEY.md §4): deterministic global
seed (OryxTest calls RandomManager.useTestSeed) and local stand-ins for the
distributed substrate — here a virtual 8-device CPU mesh via
xla_force_host_platform_device_count, the analogue of Spark master=local[3]
in AbstractLambdaIT.
"""

import os
import sys

# Must be set before jax initializes its backends.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

from oryx_tpu.common.rng import RandomManager  # noqa: E402


@pytest.fixture(autouse=True)
def _deterministic_seed():
    RandomManager.use_test_seed(1234)
    yield
    RandomManager.clear_test_seed()
