#!/usr/bin/env python
"""Measure the Spark-MLlib ALS baseline for BASELINE.md's north-star ratio.

The reference delegates batch training to Spark MLlib and publishes no
wall-clock numbers (docs/docs/performance.html, "Batch Layer"); the
target "ALS build at MovieLens-25M scale >= 20x faster than Spark-MLlib"
therefore needs a freshly measured denominator. This runner executes the
reference's exact training call — `new ALS().setRank(features)
.setIterations(iterations).setLambda(lambda).setImplicitPrefs(true)
.setAlpha(alpha)` (reference ALSUpdate.java:140-151) — via
pyspark.mllib.recommendation.ALS.trainImplicit on the SAME synthesized
dataset (oryx_tpu/ml/synth.py, same seed) the TPU bench trains on.

Usage (any host with pyspark; the TPU bench host has no egress to
install it, so this ships as a runner + instructions):

    pip install pyspark
    python tools/spark_baseline.py                    # full ML-25M shape
    python tools/spark_baseline.py --interactions 1000000   # smoke
    python tools/spark_baseline.py --master 'local[32]'

Prints ONE JSON line:
    {"metric": "spark_mllib_als_build_seconds", "value": N, ...}
Feed that value to bench.py via ORYX_SPARK_BASELINE_S=<N> to populate
speedup_vs_mllib in the bench artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--users", type=int, default=162_000)
    ap.add_argument("--items", type=int, default=59_000)
    ap.add_argument("--interactions", type=int, default=25_000_000)
    ap.add_argument("--features", type=int, default=50)
    ap.add_argument("--iterations", type=int, default=10)
    ap.add_argument("--lam", type=float, default=0.01)
    ap.add_argument("--alpha", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--master", default=f"local[{os.cpu_count() or 8}]",
        help="Spark master (default: local[all cores] — the closest "
        "single-host analogue to the reference's YARN deployment)",
    )
    args = ap.parse_args()

    try:
        from pyspark import SparkConf, SparkContext
        from pyspark.mllib.recommendation import ALS, Rating
    except ImportError:
        print(
            json.dumps(
                {
                    "metric": "spark_mllib_als_build_seconds",
                    "value": None,
                    "unit": "s",
                    "error": "pyspark not installed on this host "
                    "(pip install pyspark, then rerun)",
                }
            )
        )
        return 2

    from oryx_tpu.ml.synth import synthesize_interactions

    print(
        f"synthesizing {args.interactions} interactions "
        f"({args.users}x{args.items}, seed {args.seed})...",
        file=sys.stderr,
    )
    users, items, values = synthesize_interactions(
        args.users, args.items, args.interactions, seed=args.seed
    )

    conf = (
        SparkConf()
        .setAppName("oryx-mllib-als-baseline")
        .setMaster(args.master)
        # mirror the reference's serialization choice (common defaults in
        # oryx deployments); everything else stays stock so the number is
        # "Spark as the reference shipped it", not a tuned Spark
        .set("spark.serializer", "org.apache.spark.serializer.KryoSerializer")
    )
    sc = SparkContext(conf=conf)
    sc.setCheckpointDir("/tmp/oryx-spark-checkpoint")
    try:
        # ship the data in slices to keep driver memory bounded
        n_slices = max(8, (args.interactions // 2_000_000) or 8)
        triples = list(
            zip(users.tolist(), items.tolist(), values.tolist())
        )
        ratings = sc.parallelize(triples, n_slices).map(
            lambda t: Rating(int(t[0]), int(t[1]), float(t[2]))
        )
        ratings.cache()
        ratings.count()  # materialize before the timed region

        t0 = time.perf_counter()
        # the reference's exact call: rank/iterations/lambda/implicit/alpha
        # per ALSUpdate.java:140-151 (checkpointInterval 5 likewise)
        model = ALS.trainImplicit(
            ratings,
            rank=args.features,
            iterations=args.iterations,
            lambda_=args.lam,
            alpha=args.alpha,
        )
        # force factor materialization — ALS.run is lazy until the factor
        # RDDs are computed
        n_u = model.userFeatures().count()
        n_i = model.productFeatures().count()
        build_s = time.perf_counter() - t0
    finally:
        sc.stop()

    print(
        json.dumps(
            {
                "metric": "spark_mllib_als_build_seconds",
                "value": round(build_s, 1),
                "unit": "s",
                "interactions": args.interactions,
                "features": args.features,
                "iterations": args.iterations,
                "implicit": True,
                "alpha": args.alpha,
                "lambda": args.lam,
                "users_factored": n_u,
                "items_factored": n_i,
                "master": args.master,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
