"""Model gate: staged adoption of published generations on one replica.

The update topic broadcasts every generation to every replica (the
lambda contract — replicas are stateless consumers), which is exactly
wrong during a canary rollout: the point of a canary is that a NEW
generation serves on one replica while the rest of the fleet keeps the
incumbent until the gate promotes it. This module is the per-process
half of that control loop (the fleet half is ``fleet/control.py``): it
sits inside ``api._dispatch_update`` — the one choke point every
MODEL/MODEL-REF/TRACE message already flows through — and decides, per
generation, whether this replica adopts it now, holds it, or rolls it
back.

Modes (``oryx.serving.model-gate.mode``):

- ``off`` (default): zero behavior change; the gate is never consulted.
- ``canary``: every stamped generation is adopted immediately (this IS
  the canary replica), but the gate keeps an adoption history of
  (model message, publish stamp) pairs so a regressing generation can
  be rolled back to its predecessor as a pure pointer swap — a
  MODEL-REF re-dispatch resolves from the artifact relay cache
  (``common/artifact.py``), re-downloading nothing, and the relay PINS
  the history's refs so the rollback target cannot be LRU-evicted
  between adoption and the rollback that needs it.
- ``hold``: a generation newer than the approved watermark is parked —
  model message and stamp buffered, nothing loaded — until
  ``approve()`` raises the watermark (the fleet controller promotes a
  canary-validated generation) or a newer generation supersedes it
  (latest-wins, like live serving). An UNARMED hold gate
  (watermark ``None``) adopts everything: a restarting replica replays
  the topic from earliest and must not hold its bootstrap model
  hostage to a controller that has not probed it yet.

Because a generation id travels on the TRACE stamp that FOLLOWS its
model on the (single-partition) update topic, the gate buffers each
MODEL/MODEL-REF until its stamp arrives and judges the pair — one
message of added latency, invisible next to model-load time.

Adoption order through the normal machinery is preserved exactly: the
model dispatches through ``api._dispatch_model`` (same retries, same
parking, same freshness hooks) and the stamp then feeds
``freshness.note_stamp`` — so generation gauges, quality-window resets
(the PR 14 guarantee that a rollback does not inherit the bad
generation's shadow samples), and ``generation`` flight events all fire
as if the gate were not there.
"""

from __future__ import annotations

import json
import logging
import threading
from collections import deque

log = logging.getLogger(__name__)

_MODES = ("off", "hold", "canary")


class ModelGateError(Exception):
    """A gate control operation could not be performed (no history to
    roll back to, bad mode); maps to an HTTP 409 at the control
    endpoint."""


class _Adoption:
    """One adopted (or held) generation: everything needed to re-apply
    it later."""

    __slots__ = ("generation", "key", "message", "stamp", "handler")

    def __init__(self, generation, key, message, stamp, handler):
        self.generation = generation
        self.key = key
        self.message = message
        self.stamp = stamp
        self.handler = handler


def _stamp_generation(stamp_message: str) -> int | None:
    try:
        gen = json.loads(stamp_message).get("generation")
    except (json.JSONDecodeError, AttributeError):
        return None
    return int(gen) if isinstance(gen, (int, float)) else None


class ModelGate:
    """Per-process staged-adoption state; all mutation under one RLock
    (the update-listener thread and the /control/model/* endpoint
    threads both drive it)."""

    def __init__(self):
        self.mode = "off"
        self.history_depth = 4
        self._lock = threading.RLock()
        # MODEL/MODEL-REF seen, its TRACE stamp not yet (key, msg, handler)
        self._awaiting: tuple[str, str, object] | None = None  # guarded-by: _lock
        # held generation awaiting approval (hold mode, latest wins)
        self._pending: _Adoption | None = None  # guarded-by: _lock
        # newest approved generation; None = unarmed (adopt everything)
        self.watermark: int | None = None  # guarded-by: _lock
        # adopted generations, oldest first, newest = currently served
        self._history: deque[_Adoption] = deque()  # guarded-by: _lock
        # generations rolled back out of service: never re-adopted
        self._vetoed: set[int] = set()  # guarded-by: _lock

    @property
    def active(self) -> bool:
        return self.mode != "off"

    def configure(self, config) -> None:
        mode = config.get_string("oryx.serving.model-gate.mode", "off")
        if mode not in _MODES:
            raise ValueError(
                f"oryx.serving.model-gate.mode must be one of {_MODES}, "
                f"got {mode!r}"
            )
        self.mode = mode
        self.history_depth = max(
            2, config.get_int("oryx.serving.model-gate.history", 4)
        )

    # -- update-listener hook (api._dispatch_update) ------------------------

    def offer(self, handler, km) -> bool:
        """Consult the gate for one MODEL/MODEL-REF/TRACE message.
        Returns True when the gate consumed it (buffered, held, or
        adopted through its own delivery); False passes the message to
        the normal dispatch path untouched."""
        with self._lock:
            if km.key in ("MODEL", "MODEL-REF"):
                prev = self._awaiting
                if prev is not None:
                    # back-to-back models with no stamp between: abnormal
                    # (every publish is MODEL then TRACE). The canary
                    # adopts the orphan like the ungated path would; a
                    # hold replica fails closed and drops it — an
                    # unstamped model has no generation to judge.
                    if self.mode == "canary":
                        log.warning(
                            "model gate: unstamped %s superseded; adopting "
                            "without a stamp", prev[0],
                        )
                        self._adopt_locked(
                            _Adoption(None, prev[0], prev[1], None, prev[2])
                        )
                    else:
                        log.warning(
                            "model gate: dropping unstamped %s (hold mode "
                            "fails closed)", prev[0],
                        )
                self._awaiting = (km.key, km.message, handler)
                return True
            if km.key != "TRACE":
                return False
            aw = self._awaiting
            if aw is None:
                # stray stamp (its model never reached us, or load was
                # parked before the gate armed): normal path handles it
                return False
            gen = _stamp_generation(km.message)
            if gen is None and _bad_stamp(km.message):
                # unparseable stamp: adopt the model the way the ungated
                # path would (model loads at arrival, stamp ignored),
                # then let the normal TRACE branch log the bad stamp
                self._awaiting = None
                self._adopt_locked(
                    _Adoption(None, aw[0], aw[1], None, aw[2])
                )
                return False
            self._awaiting = None
            entry = _Adoption(gen, aw[0], aw[1], km.message, aw[2])
            if gen is not None and gen in self._vetoed:
                log.warning(
                    "model gate: generation %s was rolled back out of "
                    "service; refusing re-adoption", gen,
                )
                return True
            if (
                self.mode == "hold"
                and self.watermark is not None
                and gen is not None
                and gen > self.watermark
            ):
                if self._pending is not None:
                    log.info(
                        "model gate: held generation %s superseded by %s",
                        self._pending.generation, gen,
                    )
                self._pending = entry
                log.info(
                    "model gate: holding generation %s (watermark %s)",
                    gen, self.watermark,
                )
                return True
            self._adopt_locked(entry)
            return True

    # -- control surface (POST /control/model/*) ----------------------------

    def approve(self, generation: int) -> dict:
        """Raise the approved watermark; a held generation at/under it is
        adopted immediately. The fleet controller calls this to ARM a
        hold replica (watermark = incumbent generation) and again to
        PROMOTE a canary-validated one."""
        with self._lock:
            if not self.active:
                raise ModelGateError("model gate is off")
            self.watermark = int(generation)
            adopted = False
            if (
                self._pending is not None
                and self._pending.generation is not None
                and self._pending.generation <= self.watermark
            ):
                entry = self._pending
                self._pending = None
                self._adopt_locked(entry)
                adopted = True
            return {
                "watermark": self.watermark,
                "adopted": adopted,
                "generation": self._current_generation_locked(),
            }

    def rollback(self, reason: str | None = None) -> dict:
        """Re-apply the PREVIOUS adopted generation: a pure pointer swap
        — the model message re-dispatches through the normal load path,
        and a MODEL-REF resolves from the (pinned) relay cache without
        re-downloading a byte. The rolled-back generation is vetoed:
        a topic replay cannot re-adopt it."""
        with self._lock:
            if not self.active:
                raise ModelGateError("model gate is off")
            if len(self._history) < 2:
                raise ModelGateError(
                    "no previous generation in the gate's history to roll "
                    "back to"
                )
            bad = self._history.pop()
            if bad.generation is not None:
                self._vetoed.add(bad.generation)
            prev = self._history[-1]
            # the watermark must drop with the pointer, or a hold gate
            # would immediately re-approve the vetoed generation's peers
            if (
                self.watermark is not None
                and prev.generation is not None
                and self.watermark > prev.generation
            ):
                self.watermark = prev.generation
            log.warning(
                "model gate: rolling back generation %s -> %s (%s)",
                bad.generation, prev.generation, reason or "operator request",
            )
            self._deliver_locked(prev)
            self._unpin_locked(bad)
            return {
                "rolled_back_to": prev.generation,
                "vetoed": bad.generation,
                "reason": reason,
            }

    def healthz_section(self) -> dict:
        """The /healthz ``model_gate`` block the fleet front's prober
        copies into /fleet/status — the controller reads canary/hold
        progress from here."""
        with self._lock:
            return {
                "mode": self.mode,
                "watermark": self.watermark,
                "pending_generation": (
                    self._pending.generation
                    if self._pending is not None else None
                ),
                "generations": [
                    a.generation for a in self._history
                ],
                "vetoed": sorted(self._vetoed),
            }

    # -- internals -----------------------------------------------------------

    def _current_generation_locked(self):  # oryxlint: holds=_lock
        return self._history[-1].generation if self._history else None

    def _adopt_locked(self, entry: _Adoption) -> None:  # oryxlint: holds=_lock
        self._deliver_locked(entry)
        self._history.append(entry)
        self._pin_locked(entry)
        while len(self._history) > self.history_depth:
            self._unpin_locked(self._history.popleft())

    def _deliver_locked(self, entry: _Adoption) -> None:  # oryxlint: holds=_lock
        """Dispatch one adoption through the NORMAL model-load machinery:
        same retries, same parking, same freshness hooks — then feed its
        stamp so generation state, quality-window resets, and the
        ``generation`` flight event fire exactly as ungated."""
        from oryx_tpu.api import _dispatch_model
        from oryx_tpu.bus.api import KeyMessage

        _dispatch_model(entry.handler, KeyMessage(entry.key, entry.message))
        if entry.stamp is None:
            return
        try:
            from oryx_tpu.common.freshness import model_freshness

            model_freshness().note_stamp(entry.stamp)
        except Exception:  # noqa: BLE001 - a bad stamp never kills adoption
            log.exception("model gate: stamp re-feed failed")

    def _pin_locked(self, entry: _Adoption) -> None:  # oryxlint: holds=_lock
        if entry.key != "MODEL-REF":
            return
        try:
            from oryx_tpu.common.artifact import artifact_relay

            artifact_relay().pin(entry.message)
        except Exception:  # noqa: BLE001 - pinning is best-effort protection
            log.exception("model gate: pin failed")

    def _unpin_locked(self, entry: _Adoption) -> None:  # oryxlint: holds=_lock
        if entry.key != "MODEL-REF":
            return
        if any(
            a.key == "MODEL-REF" and a.message == entry.message
            for a in self._history
        ):
            return  # another history entry still needs this artifact
        try:
            from oryx_tpu.common.artifact import artifact_relay

            artifact_relay().unpin(entry.message)
        except Exception:  # noqa: BLE001
            log.exception("model gate: unpin failed")


def _bad_stamp(message: str) -> bool:
    try:
        doc = json.loads(message)
    except json.JSONDecodeError:
        return True
    return not isinstance(doc, dict) or not isinstance(
        doc.get("published_ms"), (int, float)
    )


_instance: ModelGate | None = None
_instance_lock = threading.Lock()


def get_model_gate() -> ModelGate:
    global _instance
    with _instance_lock:
        if _instance is None:
            _instance = ModelGate()
        return _instance


def configure_model_gate(config) -> ModelGate:
    gate = get_model_gate()
    gate.configure(config)
    return gate
