"""Golden-transcript contract tests for the Kafka wire client
(round-3 verdict #6).

The client (oryx_tpu/bus/kafka.py) previously validated only against the
in-repo protocol fake — same author on both ends. Here it speaks to a
DUMB byte replayer: recorded response bytes from
tests/data/kafka_transcripts.json (provenance in the file: either
captured from a real broker via tools/kafka_transcripts.py `record`, or
synthesized by that tool's independent spec-level implementation — own
varint/zigzag, own CRC-32C, own RecordBatch v2 builder, zero oryx
imports). The replayer contains no protocol logic: it parses only the
request header (with the INDEPENDENT parser), patches the correlation id
and the recorded broker-address fields, and writes the recorded bytes.
The produce path goes further: the replayer hands the client's
RecordBatch bytes to the independent decoder, which validates the
CRC-32C and record layout the client emitted.
"""

from __future__ import annotations

import json
import socket
import struct
import sys
import threading
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))

import kafka_transcripts as indep  # noqa: E402 - the independent impl

from oryx_tpu.bus.kafka import KafkaBroker  # noqa: E402

DOC = json.loads((ROOT / "tests" / "data" / "kafka_transcripts.json").read_text())
TOPIC = DOC["topic"]
BY_KEY = {e["api_key"]: e for e in DOC["exchanges"].values()}
# a live-broker recording carries only the happy-path captures; the edge
# tests below skip rather than break the documented record-mode refresh
EDGES = DOC.get("edge_exchanges", {})
needs_edges = pytest.mark.skipif(
    not EDGES, reason="transcript has no edge_exchanges (live recording)"
)


class Replayer:
    """Byte-level replay server: answers every request with the recorded
    response for its api key, correlation id and address fields patched.
    Records what the client sent for the tests to assert on.

    `overrides` swaps in edge exchanges by api key; an exchange carrying
    `response_seq_hex` is served in order, sticky on the last entry —
    a broker whose state changes between requests (leader moved, log
    truncated)."""

    def __init__(self, overrides: dict[int, dict] | None = None):
        self.exchanges = dict(BY_KEY)
        self.exchanges.update(overrides or {})
        self._seq: dict[int, int] = {}
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(16)
        self.port = self.sock.getsockname()[1]
        self.requests: list[tuple[int, int, str | None, bytes]] = []
        self.lock = threading.Lock()
        self._stop = False
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while True:
                head = b""
                while len(head) < 4:
                    chunk = conn.recv(4 - len(head))
                    if not chunk:
                        return
                    head += chunk
                (n,) = struct.unpack(">i", head)
                body = b""
                while len(body) < n:
                    chunk = conn.recv(n - len(body))
                    if not chunk:
                        return
                    body += chunk
                key, ver, corr, cid, rest = indep.parse_request_header(body)
                with self.lock:
                    self.requests.append((key, ver, cid, rest))
                    ex = self.exchanges.get(key)
                    if ex is None:
                        return  # unknown api: drop the connection loudly
                    if "response_seq_hex" in ex:
                        seq = ex["response_seq_hex"]
                        at = self._seq.get(key, 0)
                        hexresp = seq[min(at, len(seq) - 1)]
                        self._seq[key] = at + 1
                    else:
                        hexresp = ex["response_hex"]
                assert ver == ex["api_version"], (
                    f"client spoke api {key} v{ver}, transcript has "
                    f"v{ex['api_version']}"
                )
                resp = bytearray(bytes.fromhex(hexresp))
                for off in ex.get("port_offsets", []):
                    resp[off : off + 4] = struct.pack(">i", self.port)
                framed = (
                    struct.pack(">i", len(resp) + 4)
                    + struct.pack(">i", corr)
                    + bytes(resp)
                )
                conn.sendall(framed)
        finally:
            conn.close()

    def close(self):
        self._stop = True
        self.sock.close()


@pytest.fixture()
def replay():
    r = Replayer()
    b = KafkaBroker([("127.0.0.1", r.port)])
    yield r, b
    b.close()
    r.close()


def test_metadata_topology_decode(replay):
    r, b = replay
    assert b.topic_exists(TOPIC)
    assert b.num_partitions(TOPIC) == 2
    keys = [k for k, *_ in r.requests]
    # the ApiVersions handshake, then metadata only
    assert keys and keys[0] == 18 and all(k in (18, 3) for k in keys)


def test_fetch_decodes_recorded_batches(replay):
    """The client must decode the transcript's RecordBatch bytes — an
    uncompressed batch and a gzip batch, null and non-null keys —
    into exactly the recorded (offset, key, value) triples."""
    _, b = replay
    recs = b.read(TOPIC, 0, 5, 100)
    assert recs == [tuple(e) for e in BY_KEY[1]["expect"]]
    # offset INSIDE the first batch: earlier records are skipped
    recs = b.read(TOPIC, 0, 7, 100)
    assert [o for o, _, _ in recs] == [7, 8, 9]
    # max_records truncation
    assert len(b.read(TOPIC, 0, 5, 2)) == 2


def test_produce_emits_valid_record_batch(replay):
    """Round-trip the client's OWN produce bytes through the independent
    decoder: framing, varints, and CRC-32C must all verify."""
    r, b = replay
    b.send_batch(
        TOPIC,
        [(None, "v-five"), ("k6", "v-six"), ("k7", "v-seven")],
        partition=0,
    )
    produce = [rest for k, _, _, rest in r.requests if k == 0]
    assert len(produce) == 1
    body = produce[0]
    # independent parse of the produce v3 body: transactional_id(nullable
    # string), acks i16, timeout i32, topic array
    pos = 0
    (tlen,) = struct.unpack_from(">h", body, pos)
    pos += 2 + max(0, tlen)
    acks, timeout = struct.unpack_from(">hi", body, pos)
    assert acks == 1
    pos += 6
    (ntopics,) = struct.unpack_from(">i", body, pos)
    pos += 4
    assert ntopics == 1
    (nlen,) = struct.unpack_from(">h", body, pos)
    name = body[pos + 2 : pos + 2 + nlen].decode()
    assert name == TOPIC
    pos += 2 + nlen
    (nparts,) = struct.unpack_from(">i", body, pos)
    pos += 4
    assert nparts == 1
    pidx, blen = struct.unpack_from(">ii", body, pos)
    assert pidx == 0
    pos += 8
    batch = body[pos : pos + blen]
    decoded = indep.decode_record_batches_indep(batch)  # validates CRC
    assert [(k, v) for _, k, v in decoded] == [
        (None, b"v-five"), (b"k6", b"v-six"), (b"k7", b"v-seven"),
    ]
    assert [o for o, _, _ in decoded] == [0, 1, 2]


def test_end_offsets_via_list_offsets(replay):
    _, b = replay
    ends = b.end_offsets(TOPIC)
    assert ends == [10, 10]
    # the exchange really used ListOffsets v1 per partition
    keys = [(k, v) for k, v, _, _ in replay[0].requests]
    assert (2, 1) in keys


def test_offset_commit_and_fetch_roundtrip(replay):
    r, b = replay
    b.commit_offsets("oryx-golden-g", TOPIC, {0: 41, 1: 7})
    got = b.get_offsets("oryx-golden-g", TOPIC)
    assert got == {int(k): v for k, v in BY_KEY[9]["expect"].items()}
    keys = {k for k, *_ in r.requests}
    assert {10, 8, 9} <= keys  # find_coordinator, commit, fetch


def test_create_and_delete_topic(replay):
    _, b = replay
    b.create_topic(TOPIC, partitions=2)
    b.delete_topic(TOPIC)
    keys = [k for k, *_ in replay[0].requests]
    assert 19 in keys and 20 in keys


def test_client_id_and_header_framing(replay):
    r, b = replay
    b.topic_exists(TOPIC)
    key, ver, cid, _ = r.requests[0]
    assert key == 18 and ver == 0  # negotiation leads every connection
    assert cid  # a non-empty client id string parsed by the
    # INDEPENDENT header parser proves request header framing
    key, ver, cid2, _ = r.requests[1]
    assert key == 3 and ver == 1 and cid2 == cid


# -- edge exchanges: broker errors, truncation, codecs, negotiation -------


def _edge_broker(*names: str):
    """A replayer serving the named edge exchanges over the happy path."""
    r = Replayer(overrides={EDGES[n]["api_key"]: EDGES[n] for n in names})
    from oryx_tpu.bus.kafka import KafkaBroker as _KB

    return r, _KB([("127.0.0.1", r.port)])


@needs_edges
def test_fetch_offset_out_of_range_resumes_from_earliest():
    """Log truncated by retention: the fetch errors OFFSET_OUT_OF_RANGE,
    the client must resolve the earliest retained offset (ListOffsets
    ts=-2) and resume there — auto.offset.reset=earliest semantics, not a
    silent forever-empty poll (ConsumeDataIterator replays from stored
    offsets that can age out)."""
    r, b = _edge_broker("fetch_offset_out_of_range", "list_offsets_earliest_8")
    try:
        recs = b.read(TOPIC, 0, 5, 100)
        assert recs == [tuple(e) for e in EDGES["fetch_offset_out_of_range"]["expect"]]
        keys = [k for k, *_ in r.requests]
        assert keys.count(1) == 2  # errored fetch, then the resumed fetch
        assert 2 in keys  # the ListOffsets earliest resolution between them
    finally:
        b.close()
        r.close()


@needs_edges
def test_fetch_not_leader_refreshes_and_recovers():
    """NOT_LEADER_OR_FOLLOWER mid-consume (leader moved): the poll returns
    empty and refreshes metadata; the next poll succeeds."""
    r, b = _edge_broker("fetch_not_leader")
    try:
        assert b.read(TOPIC, 0, 5, 100) == []
        meta_after_first = [k for k, *_ in r.requests].count(3)
        recs = b.read(TOPIC, 0, 5, 100)
        assert recs == [tuple(e) for e in EDGES["fetch_not_leader"]["expect"]]
        # the error triggered a metadata refresh beyond the initial lookup
        assert meta_after_first >= 2
    finally:
        b.close()
        r.close()


@needs_edges
def test_metadata_unknown_topic():
    r, b = _edge_broker("metadata_unknown_topic")
    try:
        assert b.topic_exists(TOPIC) is False
        with pytest.raises(Exception) as ei:
            b.num_partitions(TOPIC)
        assert "3" in str(ei.value) or "UNKNOWN" in str(ei.value).upper()
    finally:
        b.close()
        r.close()


@needs_edges
def test_fetch_truncated_partial_batch():
    """A record set cut mid-batch at the max_bytes boundary: the complete
    leading batch decodes, the partial tail is ignored."""
    r, b = _edge_broker("fetch_truncated")
    try:
        recs = b.read(TOPIC, 0, 5, 100)
        assert recs == [tuple(e) for e in EDGES["fetch_truncated"]["expect"]]
    finally:
        b.close()
        r.close()


@needs_edges
def test_fetch_all_compression_codecs():
    """One batch per codec the client claims — gzip and snappy bytes from
    the independent tool's own encoders, lz4-frame and zstd from its own
    ctypes bindings (no shared code with the client's decoders)."""
    r, b = _edge_broker("fetch_codecs")
    try:
        recs = b.read(TOPIC, 0, 10, 100)
        assert recs == [tuple(e) for e in EDGES["fetch_codecs"]["expect"]]
    finally:
        b.close()
        r.close()


@needs_edges
def test_api_versions_rejects_broker_without_fetch_v4():
    """A broker advertising Fetch max v3 cannot serve this client: the
    per-connection handshake must fail the very first operation with
    UNSUPPORTED_VERSION instead of letting a garbled fetch through."""
    from oryx_tpu.bus.kafka import KafkaError

    r, b = _edge_broker("api_versions_no_fetch_v4")
    try:
        with pytest.raises((KafkaError, ConnectionError)) as ei:
            b.topic_exists(TOPIC)
        assert "35" in str(ei.value) or "support" in str(ei.value)
    finally:
        b.close()
        r.close()
