"""k-means batch tier: full model rebuild per generation.

Replaces KMeansUpdate (app/oryx-app-mllib .../kmeans/KMeansUpdate.java):
vectorize via InputSchema, train on device (ops.kmeans pjit Lloyd's with
k-means|| init), publish an artifact holding the centers tensor + cluster
sizes, and evaluate with the configured strategy over train+test
(KMeansUpdate.java:135-173; DB and SSE negated so higher = better).
"""

from __future__ import annotations

from typing import Any, Sequence

from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.common.artifact import ModelArtifact
from oryx_tpu.common.config import Config
from oryx_tpu.ml.update import MLUpdate
from oryx_tpu.ops.kmeans import (
    davies_bouldin_index,
    dunn_index,
    silhouette_coefficient,
    sum_squared_error,
    train_kmeans,
)
from oryx_tpu.apps.kmeans.common import KMeansConfig, vectorize_rows
from oryx_tpu.apps.schema import InputSchema

class KMeansUpdate(MLUpdate):
    def __init__(self, config: Config, mesh=None):
        super().__init__(config)
        self.kmeans = KMeansConfig.from_config(config)
        self.schema = InputSchema(config)
        if mesh is None:
            from oryx_tpu.parallel.distributed import mesh_from_config

            mesh = mesh_from_config(config)
        self.mesh = mesh

    def hyperparam_ranges(self) -> dict[str, Any]:
        return {"k": self.kmeans.k}

    def build_model(
        self, train: Sequence[KeyMessage], hyperparams: dict[str, Any]
    ) -> ModelArtifact:
        points = vectorize_rows(self.schema, (km.message for km in train))
        if len(points) == 0:
            raise ValueError("no parseable points")
        m = train_kmeans(
            points,
            k=int(hyperparams["k"]),
            iterations=self.kmeans.iterations,
            init=self.kmeans.init_strategy,
            mesh=self._build_mesh(),
            runs=self.kmeans.runs,
        )
        art = ModelArtifact(
            "kmeans",
            extensions={"k": str(len(m.centers))},
            tensors={"centers": m.centers},
        )
        art.content["counts"] = [int(c) for c in m.counts]
        art.content["featureNames"] = self.schema.feature_names
        return art

    def evaluate(self, model: ModelArtifact, train, test) -> float:
        points = vectorize_rows(
            self.schema,
            (km.message for part in (train, test) for km in part),
        )
        if len(points) == 0:
            return float("nan")
        centers = model.tensors["centers"]
        strategy = self.kmeans.eval_strategy
        if strategy == "DAVIES_BOULDIN":
            return -davies_bouldin_index(points, centers)
        if strategy == "DUNN":
            return dunn_index(points, centers)
        if strategy == "SILHOUETTE":
            return silhouette_coefficient(points, centers)
        if strategy == "SSE":
            return -sum_squared_error(points, centers)
        raise ValueError(f"unknown evaluation strategy: {strategy}")
