"""Clustering REST endpoints — parity with the reference's generic
clustering resources (app/oryx-app-serving .../clustering/{Assign,
DistanceToNearest,Add}.java):

  GET  /assign/{datum}            -> assigned cluster ID
  POST /assign                    -> one ID per input line
  GET  /distanceToNearest/{datum} -> distance to closest centroid
  POST /add  (or /add/{datum})    -> send data points to the input topic
"""

from __future__ import annotations

from oryx_tpu.serving.app import OryxServingException, Request, ServingApp


def _vectorize_or_400(model, datum: str):
    try:
        return model.vectorize(datum)
    except ValueError as e:
        raise OryxServingException(400, f"bad datum: {e}") from None


def register(app: ServingApp) -> None:
    @app.route("GET", "/assign/{datum}")
    def assign(a: ServingApp, req: Request):
        model = a.get_serving_model()
        cid, _ = model.closest_cluster(_vectorize_or_400(model, req.params["datum"]))
        return str(cid)

    @app.route("POST", "/assign")
    def assign_post(a: ServingApp, req: Request):
        model = a.get_serving_model()
        out = []
        for line in req.body_text().splitlines():
            line = line.strip()
            if line:
                cid, _ = model.closest_cluster(_vectorize_or_400(model, line))
                out.append(str(cid))
        if not out:
            raise OryxServingException(400, "no data points given")
        return out

    @app.route("GET", "/distanceToNearest/{datum}")
    def distance_to_nearest(a: ServingApp, req: Request):
        model = a.get_serving_model()
        _, dist = model.closest_cluster(_vectorize_or_400(model, req.params["datum"]))
        return str(dist)

    @app.route("POST", "/add/{datum}")
    def add_one(a: ServingApp, req: Request):
        a.send_input(req.params["datum"])
        return 200, None

    @app.route("POST", "/add")
    def add(a: ServingApp, req: Request):
        from oryx_tpu.serving.resources.common import send_input_lines

        send_input_lines(a, req.body_text())
        return 200, None

    def _clustering_console(a: ServingApp) -> list[tuple[str, object]]:
        model = a.get_serving_model()
        counts = getattr(model, "counts", None)
        rows: list[tuple[str, object]] = [("clusters", model.num_clusters)]
        if counts is not None:
            import numpy as _np

            c = _np.asarray(counts)
            rows += [
                ("points assigned", int(c.sum())),
                ("largest cluster", int(c.max()) if c.size else 0),
                ("smallest cluster", int(c.min()) if c.size else 0),
            ]
        return rows

    app.console_sections.append(("Clustering model", _clustering_console))
