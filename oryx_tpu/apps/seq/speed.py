"""Seq speed tier: fold new/extended sessions into the serving state.

Per micro-batch: group the window's events into sessions, stitch each
onto the bounded per-session tail this manager remembers, run the GRU
over every (context -> next item) transition, and nudge the TARGET
item's embedding toward the context's hidden state — one bounded blend
step ``e <- (1-eta) e + eta h``. Each touched item becomes ONE UP
["E", id, [vec]] message, so the published update is sized by the dirty
rows (the delta contract: serving applies them as row scatters, never a
model re-upload). Items never seen by the batch model enter the store
at the context's hidden state — a brand-new item becomes recommendable
one micro-batch after its first click, the seq analogue of ALS folding
in a brand-new user.

Like ALS, build_updates only READS the model state: the emitted UP
messages loop back through the update topic into every consumer
(including this one), which is what keeps N serving replicas and this
manager bit-identical. The one manager-local piece — the bounded
session-tail memory — advances only AFTER every fallible step, because
the speed layer replays failed windows (rewind, then bisection): tails
mutated before a raise would stitch bogus contexts into the replay.
"""

from __future__ import annotations

import logging

import numpy as np

from oryx_tpu.api import AbstractSpeedModelManager
from oryx_tpu.common.config import Config
from oryx_tpu.common.locks import RateLimitCheck
from oryx_tpu.common.metrics import get_registry
from oryx_tpu.ops.seq import encode_sessions
from oryx_tpu.apps.seq.common import (
    SeqConfig,
    parse_session_events,
    sessionize,
    valid_session_line,
    valid_session_lines,
)
from oryx_tpu.apps.seq.state import SeqState, apply_seq_update
from oryx_tpu.apps.updates import batch_update_messages

log = logging.getLogger(__name__)


class SeqSpeedModelManager(AbstractSpeedModelManager):
    def __init__(self, config: Config):
        self.config = config
        self.seq = SeqConfig.from_config(config)
        self.min_fraction = config.get_float("oryx.speed.min-model-load-fraction", 0.8)
        self.state: SeqState | None = None
        self._not_ready_log = RateLimitCheck(60.0)
        # bounded session-tail memory: session key -> (recent item list,
        # newest folded (ts, item) pairs); insertion-ordered dict as
        # LRU, live sessions re-insert
        self._tails: dict[str, tuple[list[str], list]] = {}
        self._m_folded = get_registry().counter(
            "oryx_seq_sessions_folded_total",
            "Sessions (new or extended) the seq speed tier folded into "
            "the serving state as item-embedding row deltas",
        )
        # the speed tier sees the raw event stream: it feeds the live
        # input sketch the drift gauges compare against the served
        # generation's training profile (common/qualitystats.py)
        from oryx_tpu.common.qualitystats import configure_qualitystats

        configure_qualitystats(config)

    # -- update-topic consumption ------------------------------------------

    def consume_key_message(self, key: str | None, message: str) -> None:
        self.state = apply_seq_update(self.state, key, message)

    def validate_record(self, km) -> bool:
        return valid_session_line(km.message)

    def validate_records(self, records):
        return valid_session_lines(km.message for km in records)

    # -- micro-batch -> updates --------------------------------------------

    def build_updates(self, new_data):
        st = self.state
        if st is None or st.fraction_loaded() < self.min_fraction:
            if self._not_ready_log.test():
                log.info("seq speed model not yet loaded; skipping micro-batch")
            return []
        users, sess, items, tss = parse_session_events(new_data)
        if len(tss) == 0:
            return []
        # input drift: fold this micro-batch's item events into the live
        # windowed sketch (one hash per event, micro-batch granularity)
        from oryx_tpu.common.qualitystats import get_qualitystats

        get_qualitystats().note_input_events(items, tss)
        window = self.seq.window
        # transitions: (context item lists, target item), context = the
        # remembered tail + this window's not-yet-folded items. The tails
        # are only READ here — they advance at the very end, after all
        # fallible work — and each tail remembers the newest folded
        # (ts, item) pairs, so a window replayed by the layer
        # (rewind/bisection inside the build, or a publish/divert failure
        # after it) re-derives zero transitions instead of stitching
        # itself onto a tail that already contains it. The pair memory is
        # bounded (pair_cap per session): a replay of a single session
        # window larger than it would re-blend its oldest events —
        # bounded over-weighting, the same honest-degraded spirit as the
        # layer's bisection mode.
        sessions_ts = sessionize(
            users, sess, items, tss, max_events=self.seq.max_session_events
        )
        pair_cap = max(4 * window, 32)
        contexts: list[list[str]] = []
        targets: list[str] = []
        ctx_keys: list[str] = []  # owning session of each transition
        new_tails: dict[str, tuple[list[str], list[tuple[int, str]]]] = {}
        for key, evs in sessions_ts.items():
            tail, seen_pairs = self._tails.get(key, ([], []))
            seen = set(seen_pairs)
            new_evs = [e for e in evs if e not in seen]
            if not new_evs:
                continue
            full = tail + [i for _, i in new_evs]
            for j in range(len(tail), len(full)):
                ctx = full[max(0, j - window) : j]
                if ctx:
                    contexts.append(ctx)
                    targets.append(full[j])
                    ctx_keys.append(key)
            new_tails[key] = (
                full[-window:], (seen_pairs + new_evs)[-pair_cap:]
            )
        if not contexts:
            self._advance_tails(new_tails)
            return []

        # gather context embeddings under one read lock per batch; items
        # absent from the store contribute zero rows (masked anyway when
        # the whole context is unknown — those transitions are skipped)
        flat: list[str] = [i for c in contexts for i in c]
        vecs, have = st.items.get_many(flat)
        # fixed compile shapes: L is always the configured window and the
        # row count pads to a power-of-two bucket, so the jitted encoder
        # compiles once per bucket instead of once per micro-batch
        mat = np.zeros((len(contexts), window, st.dim), dtype=np.float32)
        mask = np.zeros((len(contexts), window), dtype=np.float32)
        pos = 0
        known_ctx = np.zeros(len(contexts), dtype=bool)
        for r, c in enumerate(contexts):
            n = len(c)
            mat[r, window - n:] = vecs[pos : pos + n]
            mask[r, window - n:] = have[pos : pos + n].astype(np.float32)
            known_ctx[r] = bool(have[pos : pos + n].any())
            pos += n
        rows = np.nonzero(known_ctx)[0]
        if rows.size == 0:
            self._advance_tails(new_tails)
            return []
        b_pad = max(16, 1 << int(rows.size - 1).bit_length())
        mat_b = np.zeros((b_pad, window, st.dim), dtype=np.float32)
        mask_b = np.zeros((b_pad, window), dtype=np.float32)
        mat_b[: rows.size] = mat[rows]
        mask_b[: rows.size] = mask[rows]
        h = encode_sessions(st.params, mat_b, mask_b)[: rows.size]

        # Reference magnitude: hidden states are tanh-bounded while
        # trained embedding rows carry the softmax's learned scale, so a
        # raw h would enter the catalog scoring ~an order of magnitude
        # low. Fold DIRECTIONS from h and magnitude from the trained
        # rows: the mean norm of the known context embeddings in this
        # batch stands in for "a trained row's scale".
        known_norms = np.linalg.norm(vecs[have], axis=1) if have.any() else None
        ref_norm = float(known_norms.mean()) if known_norms is not None and known_norms.size else 1.0
        if not np.isfinite(ref_norm) or ref_norm <= 0:
            ref_norm = 1.0

        # one blended row per touched item (the last write wins within a
        # micro-batch, matching per-event application order); the current
        # target rows gather in ONE get_many (one read lock per batch,
        # never one per touched item)
        eta = self.seq.fold_rate
        touched = sorted({targets[int(r)] for r in rows})
        cur_vecs, cur_have = st.items.get_many(touched)
        current = {
            t: (cur_vecs[j] if cur_have[j] else None)
            for j, t in enumerate(touched)
        }
        new_rows: dict[str, np.ndarray] = {}
        for hr, r in zip(h, rows):
            target = targets[int(r)]
            hn = float(np.linalg.norm(hr))
            step = hr * (ref_norm / hn) if hn > 1e-12 else hr
            cur = new_rows.get(target)
            if cur is None:
                stored = current[target]
                cur = stored if stored is not None else step
            new_rows[target] = (1.0 - eta) * cur + eta * step
        ids = sorted(new_rows)
        block = np.stack([new_rows[i] for i in ids])
        finite = np.isfinite(block).all(axis=1)
        if not finite.all():
            keep = np.nonzero(finite)[0]
            ids = [ids[int(j)] for j in keep]
            block = block[keep]
        if not ids:
            self._advance_tails(new_tails)
            return []
        out = batch_update_messages("E", ids, block)
        # everything fallible inside this call is done: NOW the session
        # tails (and their folded-pair memories) advance. The counter
        # counts sessions that actually CONTRIBUTED an embedding delta
        # (known-context transitions), matching its documented meaning —
        # first-click and unknown-context sessions advance tails only.
        self._advance_tails(new_tails)
        self._m_folded.inc(len({ctx_keys[int(r)] for r in rows}))
        return out

    def _advance_tails(
        self, new_tails: dict[str, tuple[list[str], list]]
    ) -> None:
        """Adopt the micro-batch's session tails (pop + reinsert keeps
        the dict's insertion order working as the LRU) and trim to the
        configured bound. Each entry is (recent items, newest folded
        (ts, item) pairs) — the pair memory makes a REPLAYED window
        (publish failure after this call, layer rewind) fold nothing a
        second time."""
        for key, tail in new_tails.items():
            self._tails.pop(key, None)
            self._tails[key] = tail
        while len(self._tails) > self.seq.max_sessions:
            self._tails.pop(next(iter(self._tails)))
