"""Locality-sensitive hashing over factor vectors — CPU-serving parity.

Mirrors the reference's LocalitySensitiveHash (app/oryx-app-serving
.../als/model/LocalitySensitiveHash.java:36-177): pick the fewest sign-bit
hyperplane hashes (<= MAX_HASHES) whose probed-partition fraction meets the
configured sample rate while still probing >= num_cores partitions; choose
hyperplanes greedily by minimum total |cos| to those already chosen;
partition index = sign-bit fingerprint of the hyperplane dots; candidates =
all partitions within max_bits_differing Hamming distance of the query's.

On TPU the exact single-matmul top-k (ops/pallas_topk.py) dominates, so LSH
is OFF by default (oryx.als.sample-rate = 1.0); it exists for CPU-bound
deployments where scoring a subsample is the difference between 7 and 437
qps (BASELINE.md LSH tables).
"""

from __future__ import annotations

import logging
import math

import numpy as np

from oryx_tpu.common.rng import RandomManager

log = logging.getLogger(__name__)

MAX_HASHES = 16
_CANDIDATES_SINCE_BEST = 1000


def _choose_hash_count(sample_rate: float, num_cores: int) -> tuple[int, int]:
    """(num_hashes, max_bits_differing): fewest hashes achieving the sample
    rate, probing as many partitions as possible while <= num_cores
    (LocalitySensitiveHash.java:44-74 — the probe count may overshoot
    num_cores by one binomial step, by design)."""
    num_hashes = 0
    bits_differing = 0
    while num_hashes < MAX_HASHES:
        bits_differing = 0
        partitions_to_try = 1
        while bits_differing < num_hashes and partitions_to_try < num_cores:
            bits_differing += 1
            partitions_to_try += math.comb(num_hashes, bits_differing)
        if bits_differing == num_hashes and partitions_to_try < num_cores:
            num_hashes += 1
            continue  # can't keep all cores busy; more hashes
        if partitions_to_try <= sample_rate * (1 << num_hashes):
            break
        num_hashes += 1
    return num_hashes, bits_differing


class LocalitySensitiveHash:
    def __init__(
        self,
        sample_rate: float,
        num_features: int,
        num_cores: int | None = None,
        max_bits_differing: int | None = None,
    ):
        if num_cores is None:
            import os

            num_cores = os.cpu_count() or 1
        num_hashes, bits_differing = _choose_hash_count(sample_rate, num_cores)
        if max_bits_differing is not None:
            # explicit oryx.als.lsh-max-bits-differing override of the
            # derived Hamming-ball radius (wider = more candidate
            # partitions probed = higher recall, lower speedup)
            bits_differing = max(0, min(int(max_bits_differing), num_hashes))
        self.max_bits_differing = bits_differing
        log.info(
            "LSH with %d hashes, querying partitions with up to %d bits differing",
            num_hashes,
            bits_differing,
        )

        rng = RandomManager.get_random()
        vectors: list[np.ndarray] = []
        for _ in range(num_hashes):
            # greedy most-orthogonal pick: keep sampling random unit vectors
            # until 1000 in a row fail to lower the total |cos| to the
            # already-chosen hyperplanes
            best_score = np.inf
            best: np.ndarray | None = None
            since_best = 0
            while since_best < _CANDIDATES_SINCE_BEST:
                cand = rng.standard_normal(num_features).astype(np.float32)
                cand /= max(float(np.linalg.norm(cand)), 1e-12)
                score = sum(abs(float(v @ cand)) for v in vectors)
                if score < best_score:
                    best = cand
                    if score == 0.0:
                        break
                    best_score = score
                    since_best = 0
                else:
                    since_best += 1
            vectors.append(best)
        # [H, F]; empty H means one partition holding everything
        self.hash_vectors = (
            np.stack(vectors) if vectors else np.zeros((0, num_features), dtype=np.float32)
        )

        # all 2^H partition indices ordered by ascending popcount, so a
        # Hamming-ball query is a prefix of this list XOR the query index
        size = 1 << num_hashes
        order = np.argsort([bin(i).count("1") * size + i for i in range(size)], kind="stable")
        self._by_popcount = np.arange(size, dtype=np.int64)[order]
        self._prefix_for_bits = np.cumsum(
            [math.comb(num_hashes, b) for b in range(num_hashes + 1)]
        )

    @property
    def num_hashes(self) -> int:
        return self.hash_vectors.shape[0]

    @property
    def num_partitions(self) -> int:
        return 1 << self.num_hashes

    def index_for(self, vector: np.ndarray) -> int:
        """Sign-bit fingerprint: bit i set iff hyperplane_i . v > 0."""
        if self.num_hashes == 0:
            return 0
        dots = self.hash_vectors @ np.asarray(vector, dtype=np.float32)
        return int(np.sum((dots > 0.0) << np.arange(self.num_hashes)))

    def indices_for(self, matrix: np.ndarray) -> np.ndarray:
        """Vectorized index_for over rows of [N, F] -> [N] int64."""
        n = matrix.shape[0]
        if self.num_hashes == 0:
            return np.zeros(n, dtype=np.int64)
        bits = (matrix.astype(np.float32) @ self.hash_vectors.T) > 0.0
        return bits @ (1 << np.arange(self.num_hashes, dtype=np.int64))

    def candidate_indices(self, vector: np.ndarray) -> np.ndarray:
        """All partition indices within max_bits_differing of the query's
        (LocalitySensitiveHash.java:156-177)."""
        main = self.index_for(vector)
        if self.max_bits_differing == self.num_hashes:
            return np.arange(self.num_partitions, dtype=np.int64)
        how_many = int(self._prefix_for_bits[self.max_bits_differing])
        return self._by_popcount[:how_many] ^ main


def measured_topn_recall(
    got_ids, query_vec: np.ndarray, mat: np.ndarray, ids, k: int
) -> float:
    """|got ∩ exact top-k| / k for ONE query: the exact top-k is rescored
    from the full matrix, so an LSH (or any approximate) answer's recall
    is MEASURED, never assumed from a sample-rate or recall-target knob.
    Used by the bench's LSH HTTP stage to exactly rescore a sample of its
    own responses (mirrors the reference's eval of hash sampling)."""
    scores = mat @ np.asarray(query_vec, dtype=np.float32)
    kk = min(k, scores.shape[0])
    top = np.argpartition(-scores, kk - 1)[:kk]
    top = top[np.argsort(-scores[top], kind="stable")]
    exact = {ids[int(j)] for j in top}
    return len(set(got_ids) & exact) / max(1, kk)
