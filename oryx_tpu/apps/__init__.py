"""Packaged apps. The registry of their wiring lives in
oryx_tpu/apps/spi.py (AppSpec / get_app / app_overlay) — imported
lazily by the CLI's --app lookup so `import oryx_tpu.apps` stays free of
app code.
"""
