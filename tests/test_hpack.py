"""HPACK codec: RFC 7541 vectors + property sweeps.

The wire-facing decoder must survive arbitrary header sets round-tripped
through our stateless encoder, Huffman-coded strings from the RFC's own
examples, and corrupted inputs failing loudly (HpackError) instead of
desyncing silently.
"""

from __future__ import annotations

import random

import pytest

from oryx_tpu.serving.hpack import (
    Decoder, HpackError, STATIC_TABLE, decode_int, encode, encode_int,
    huffman_decode,
)


def test_rfc7541_huffman_vectors():
    # C.4.x / C.6.x request+response strings
    cases = [
        ("f1e3c2e5f23a6ba0ab90f4ff", b"www.example.com"),
        ("a8eb10649cbf", b"no-cache"),
        ("25a849e95ba97d7f", b"custom-key"),
        ("25a849e95bb8e8b4bf", b"custom-value"),
        ("6402", b"302"),
        ("aec3771a4b", b"private"),
        ("d07abe941054d444a8200595040b8166e082a62d1bff", b"Mon, 21 Oct 2013 20:13:21 GMT"),
        ("9d29ad171863c78f0b97c8e9ae82ae43d3", b"https://www.example.com"),
    ]
    for hex_in, want in cases:
        assert huffman_decode(bytes.fromhex(hex_in)) == want


def test_integer_coding_roundtrip():
    for prefix in (4, 5, 6, 7):
        for v in (0, 1, (1 << prefix) - 2, (1 << prefix) - 1, (1 << prefix),
                  127, 128, 255, 300, 16384, 10_000_000):
            data = encode_int(v, prefix)
            got, pos = decode_int(data, 0, prefix)
            assert got == v and pos == len(data), (prefix, v)


def test_property_roundtrip_random_header_sets():
    rng = random.Random(7)
    static_names = [n for n, _ in STATIC_TABLE]
    for _ in range(200):
        headers = []
        for _ in range(rng.randrange(0, 12)):
            if rng.random() < 0.4:
                name = rng.choice(static_names)
            else:
                name = bytes(
                    rng.randrange(0x21, 0x7F) for _ in range(rng.randrange(1, 20))
                ).lower()
            value = bytes(
                rng.randrange(0, 256) for _ in range(rng.randrange(0, 200))
            )
            headers.append((name, value))
        assert Decoder().decode(encode(headers)) == headers


def test_corruption_raises_not_desyncs():
    block = encode([(b":status", b"200"), (b"x-a", b"b" * 100)])
    for cut in (1, len(block) // 2, len(block) - 1):
        with pytest.raises((HpackError, EOFError, IndexError)):
            Decoder().decode(block[:cut] + b"\x7f\xff\xff\xff\xff\xff")
    # oversized table-size update beyond the settings cap
    with pytest.raises(HpackError):
        Decoder(max_table_size=256).decode(bytes([0x3F, 0xE1, 0xFF, 0x03]))


def test_dynamic_table_eviction():
    d = Decoder(max_table_size=64)  # tiny: ~1 entry (32B overhead each)
    # two literal-with-incremental-indexing entries; the first must evict
    def lit_inc(name: bytes, value: bytes) -> bytes:
        out = bytearray([0x40])
        out += encode_int(len(name), 7) + name
        out += encode_int(len(value), 7) + value
        return bytes(out)

    d.decode(lit_inc(b"aaaa", b"1111"))
    d.decode(lit_inc(b"bbbb", b"2222"))
    assert len(d._dyn) == 1 and d._dyn[0] == (b"bbbb", b"2222")
    # indexed reference to the surviving entry (static size + 1)
    idx = len(STATIC_TABLE) + 1
    got = d.decode(encode_int(idx, 7, 0x80))
    assert got == [(b"bbbb", b"2222")]
