"""kafka:// backend: wire codec units + full bus contract over real TCP.

Mirrors the reference's strategy of testing against a real in-process
broker (LocalKafkaBroker) instead of mocks: every test here goes through
actual sockets speaking the Kafka wire protocol. Set ORYX_KAFKA_BROKER
(host:port) to additionally run the contract against an external cluster.
"""

import os
import struct

import pytest

from oryx_tpu.bus.api import ConsumeDataIterator, KeyMessage, TopicProducer
from oryx_tpu.bus.broker import get_broker, partition_for
from oryx_tpu.bus.kafka import KafkaBroker, parse_bootstrap
from oryx_tpu.bus.kafkawire import (
    crc32c,
    decode_record_batches,
    encode_record_batch,
)
from tests.kafka_testbroker import LocalKafkaTestBroker


# -- codec units ------------------------------------------------------------

def test_crc32c_check_value():
    from oryx_tpu.bus.kafkawire import _crc32c_py

    # the canonical CRC-32C check vector, for whichever impl is active AND
    # the pure-python slicing-by-8 fallback explicitly
    for fn in (crc32c, _crc32c_py):
        assert fn(b"123456789") == 0xE3069283
        assert fn(b"") == 0
    # both impls agree across lengths that hit the 8-byte and tail loops
    import os

    for n in (1, 7, 8, 9, 255, 1024, 4097):
        blob = os.urandom(n)
        assert crc32c(blob) == _crc32c_py(blob)


def test_record_batch_roundtrip():
    recs = [(b"k1", b"v1"), (None, b"v2"), (b"k3", None), (b"", b"")]
    batch = encode_record_batch(recs, base_timestamp_ms=1234)
    # header spot checks against the spec layout
    assert struct.unpack_from(">q", batch, 0)[0] == 0  # baseOffset
    assert batch[16] == 2  # magic v2
    out = decode_record_batches(batch)
    assert out == [(0, b"k1", b"v1"), (1, None, b"v2"), (2, b"k3", None), (3, b"", b"")]


def test_record_batch_decode_tolerates_partial_tail():
    batch = encode_record_batch([(b"a", b"b")], 0)
    # a second batch truncated mid-header, as a broker may return
    data = batch + batch[: len(batch) // 2]
    assert decode_record_batches(data) == [(0, b"a", b"b")]


def test_decode_after_base_offset_rewrite():
    """The broker assigns offsets by rewriting baseOffset; decode must
    yield absolute offsets."""
    batch = encode_record_batch([(b"a", b"1"), (b"b", b"2")], 0)
    rewritten = struct.pack(">q", 100) + batch[8:]
    assert [o for o, _, _ in decode_record_batches(rewritten)] == [100, 101]


def test_parse_bootstrap():
    assert parse_bootstrap("kafka://h1:9092") == [("h1", 9092)]
    assert parse_bootstrap("kafka://h1:9092,h2:9093") == [("h1", 9092), ("h2", 9093)]
    assert parse_bootstrap("kafka://justhost") == [("justhost", 9092)]


# -- contract over TCP ------------------------------------------------------

@pytest.fixture
def kafka():
    with LocalKafkaTestBroker() as server:
        broker = KafkaBroker([(server.host, server.port)])
        broker._test_server = server  # fidelity knobs for the fault tests
        yield broker
        broker.close()


def test_admin_roundtrip(kafka):
    assert not kafka.topic_exists("T")
    kafka.create_topic("T", partitions=3)
    assert kafka.topic_exists("T")
    assert kafka.num_partitions("T") == 3
    with pytest.raises(ValueError):
        kafka.create_topic("T")
    kafka.delete_topic("T")
    assert not kafka.topic_exists("T")


def test_produce_fetch_keyed_partitioning(kafka):
    kafka.create_topic("T", partitions=4)
    for i in range(40):
        kafka.send("T", f"k{i}", f"m{i}")
    # every record lands on its crc32-keyed partition
    seen = {}
    for p in range(4):
        for off, key, msg in kafka.read("T", p, 0, 1000):
            assert partition_for(key, 4) == p
            seen[key] = (p, off, msg)
    assert len(seen) == 40
    assert seen["k7"][2] == "m7"
    # offsets are per-partition contiguous from 0
    ends = kafka.end_offsets("T")
    assert sum(ends) == 40
    for p in range(4):
        offs = [o for o, _, _ in kafka.read("T", p, 0, 1000)]
        assert offs == list(range(ends[p]))


def test_read_from_mid_offset_and_max_records(kafka):
    kafka.create_topic("T", partitions=1)
    kafka.send_batch("T", [(None, f"m{i}") for i in range(10)])
    recs = kafka.read("T", 0, 4, 3)
    assert [(o, m) for o, _, m in recs] == [(4, "m4"), (5, "m5"), (6, "m6")]
    assert kafka.read("T", 0, 10, 5) == []  # at end: empty, not error


def test_group_offsets(kafka):
    kafka.create_topic("T", partitions=2)
    assert kafka.get_offsets("g1", "T") == {}
    kafka.commit_offsets("g1", "T", {0: 5, 1: 7})
    assert kafka.get_offsets("g1", "T") == {0: 5, 1: 7}
    kafka.commit_offsets("g1", "T", {0: 6})
    assert kafka.get_offsets("g1", "T")[0] == 6
    assert kafka.get_offsets("g2", "T") == {}  # groups isolated


def test_consume_iterator_over_kafka(kafka):
    kafka.create_topic("T", partitions=2)
    prod = TopicProducer(kafka, "T")
    for i in range(6):
        prod.send(f"k{i}", f"m{i}")
    with ConsumeDataIterator(kafka, "T", group="g", start="earliest") as it:
        got = {next(it).message for _ in range(6)}
        assert got == {f"m{i}" for i in range(6)}
        it.commit()
    # committed resume: only new messages are seen
    prod.send("k9", "m9")
    with ConsumeDataIterator(kafka, "T", group="g", start="committed") as it2:
        assert next(it2) == KeyMessage("k9", "m9")


def test_get_broker_resolves_and_caches_kafka_uri():
    with LocalKafkaTestBroker() as server:
        a = get_broker(server.uri)
        b = get_broker(server.uri)
        assert a is b
        assert isinstance(a, KafkaBroker)
        a.create_topic("X", partitions=1)
        a.send("X", None, "hello")
        assert a.read("X", 0, 0, 10)[0][2] == "hello"


def test_large_message_roundtrip(kafka):
    """An oversized MODEL payload (multi-MB) survives produce/fetch."""
    kafka.create_topic("T", partitions=1, max_message_bytes=32 << 20)
    big = "x" * (5 << 20)
    kafka.send("T", "MODEL", big)
    recs = kafka.read("T", 0, 0, 1)
    assert recs[0][1] == "MODEL" and recs[0][2] == big


def test_unicode_and_empty_payloads(kafka):
    kafka.create_topic("T", partitions=1)
    kafka.send("T", "clé", "värde-☃")
    kafka.send("T", None, "")
    recs = kafka.read("T", 0, 0, 10)
    assert recs[0][1] == "clé" and recs[0][2] == "värde-☃"
    assert recs[1][1] is None and recs[1][2] == ""


# -- the full lambda slice over kafka:// ------------------------------------

def test_e2e_batch_to_serving_over_kafka(tmp_path):
    """Batch layer trains and publishes over a kafka:// update topic; the
    serving layer replays it and answers /recommend — the deployment
    topology of the reference with a real wire protocol in between."""
    import json
    import time
    import urllib.request

    import numpy as np

    from oryx_tpu.apps.als.batch import ALSUpdate
    from oryx_tpu.apps.als.serving import ALSServingModelManager
    from oryx_tpu.bus.broker import topics
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.rng import RandomManager
    from oryx_tpu.layers import BatchLayer
    from oryx_tpu.serving.server import ServingLayer

    RandomManager.use_test_seed(11)
    with LocalKafkaTestBroker() as server:
        uri = server.uri
        cfg = load_config(
            overlay={
                "oryx.id": "kafka-e2e",
                "oryx.input-topic.broker": uri,
                "oryx.update-topic.broker": uri,
                "oryx.batch.storage.data-dir": str(tmp_path / "data"),
                "oryx.batch.storage.model-dir": str(tmp_path / "model"),
                "oryx.serving.api.port": 0,
                "oryx.serving.application-resources": [
                    "oryx_tpu.serving.resources.common",
                    "oryx_tpu.serving.resources.als",
                ],
                "oryx.als.hyperparams.features": 4,
                "oryx.als.hyperparams.iterations": 4,
                "oryx.ml.eval.test-fraction": 0.1,
                "oryx.serving.min-model-load-fraction": 0.8,
            }
        )
        topics.maybe_create(uri, "OryxInput", partitions=2)
        topics.maybe_create(uri, "OryxUpdate", partitions=1)
        broker = get_broker(uri)

        rng = np.random.default_rng(0)
        prod = TopicProducer(broker, "OryxInput")
        for u in range(24):
            for i in rng.choice(16, 4, replace=False):
                prod.send(f"u{u}", f"u{u},i{i},1,{1000 + int(i)}")

        batch = BatchLayer(cfg, update=ALSUpdate(cfg))
        batch.ensure_streams()
        batch._consumer.seek({p: 0 for p in batch._consumer.positions()})
        n = batch.run_generation(timestamp_ms=1_700_000_000_000)
        assert n == 24 * 4
        batch.close()

        serving = ServingLayer(cfg, model_manager=ALSServingModelManager(cfg))
        serving.start()
        try:
            base = f"http://127.0.0.1:{serving.port}"
            deadline = time.time() + 30
            status = None
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(f"{base}/ready", timeout=5) as resp:
                        status = resp.status
                        break
                except urllib.error.HTTPError as e:
                    status = e.code
                    if status != 503:
                        break
                time.sleep(0.2)
            assert status == 200, f"serving never ready over kafka ({status})"
            with urllib.request.urlopen(f"{base}/recommend/u5?howMany=3", timeout=10) as resp:
                recs = json.loads(resp.read())
            assert len(recs) == 3
        finally:
            serving.close()


# -- external cluster (opt-in) ----------------------------------------------

@pytest.mark.skipif(
    not os.environ.get("ORYX_KAFKA_BROKER"),
    reason="set ORYX_KAFKA_BROKER=host:port to test against a real cluster",
)
def test_contract_against_external_cluster():
    import uuid

    broker = KafkaBroker(parse_bootstrap(f"kafka://{os.environ['ORYX_KAFKA_BROKER']}"))
    topic = f"oryx-test-{uuid.uuid4().hex[:12]}"
    broker.create_topic(topic, partitions=2)
    try:
        broker.send(topic, "k", "v")
        assert any(
            broker.read(topic, p, 0, 10) for p in range(2)
        )
        broker.commit_offsets("oryx-test-g", topic, {0: 1})
        assert broker.get_offsets("oryx-test-g", topic)[0] == 1
    finally:
        broker.delete_topic(topic)
        broker.close()


def test_truncated_log_reset(tmp_path):
    """A consumer starting at offset 0 on a retention-truncated partition
    must resume from the earliest retained offset, not stall forever."""
    with LocalKafkaTestBroker() as server:
        broker = KafkaBroker([(server.host, server.port)])
        broker.create_topic("T", partitions=1)
        # two separate batches so truncation can drop the first whole batch
        broker.send_batch("T", [(None, f"a{i}") for i in range(5)])
        broker.send_batch("T", [(None, f"b{i}") for i in range(5)])
        server.truncate("T", 0, 5)
        recs = broker.read("T", 0, 0, 100)
        assert [m for _, _, m in recs] == [f"b{i}" for i in range(5)]
        assert recs[0][0] == 5  # real offsets, post-truncation
        broker.close()


def test_speed_layer_folds_over_kafka():
    """The speed tier over the kafka wire protocol: replay the model from
    the update topic, fold a fresh interaction from the input topic, and
    publish the UP deltas back — the last tier not yet exercised against
    kafka://."""
    import json
    import time

    import numpy as np

    from oryx_tpu.apps.als.common import x_update_message, y_update_message
    from oryx_tpu.apps.als.speed import ALSSpeedModelManager
    from oryx_tpu.bus.broker import topics
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.rng import RandomManager
    from oryx_tpu.layers import SpeedLayer

    RandomManager.use_test_seed(21)
    with LocalKafkaTestBroker() as server:
        uri = server.uri
        cfg = load_config(
            overlay={
                "oryx.id": "kafka-speed",
                "oryx.input-topic.broker": uri,
                "oryx.update-topic.broker": uri,
                "oryx.speed.streaming.generation-interval-sec": 1,
                "oryx.speed.min-model-load-fraction": 0.8,
                "oryx.als.hyperparams.features": 4,
            }
        )
        topics.maybe_create(uri, "OryxInput", partitions=2)
        topics.maybe_create(uri, "OryxUpdate", partitions=1)
        broker = get_broker(uri)

        # scripted model on the update topic (MockALSModelUpdateGenerator
        # pattern): MODEL header then the factor flood
        rng = np.random.default_rng(5)
        prod = TopicProducer(broker, "OryxUpdate")
        prod.send(
            "MODEL",
            json.dumps({"app": "als", "extensions": {"features": "4"}, "content": {}}),
        )
        for u in range(6):
            k, m = x_update_message(f"u{u}", rng.standard_normal(4), [f"i{u}"])
            prod.send(k, m)
        for i in range(8):
            k, m = y_update_message(f"i{i}", rng.standard_normal(4))
            prod.send(k, m)

        speed = SpeedLayer(cfg, manager=ALSSpeedModelManager(cfg))
        speed.start()
        try:
            # wait for model load via replay, then feed one interaction
            deadline = time.time() + 30
            while time.time() < deadline:
                st = speed.manager.state
                if st is not None and st.fraction_loaded() >= 0.8:
                    break
                time.sleep(0.2)
            else:
                raise AssertionError("speed model never loaded over kafka")
            TopicProducer(broker, "OryxInput").send("u1", "u1,i2,3,99")

            # the micro-batch loop publishes X/Y deltas to the update topic
            deadline = time.time() + 30
            got = []
            while time.time() < deadline:
                recs = broker.read("OryxUpdate", 0, 0, 200)
                got = [
                    json.loads(m)
                    for _, kk, m in recs
                    if kk == "UP" and json.loads(m)[1] in ("u1", "i2")
                ]
                # the scripted flood also carries u1/i2 rows; fold deltas
                # arrive AFTER the input send, so expect more than the 2
                if len(got) >= 4:
                    break
                time.sleep(0.3)
            # the scripted flood alone contributes exactly two u1/i2 rows;
            # anything beyond proves the micro-batch FOLD published deltas
            assert len(got) >= 4, got
            kinds = {(g[0], g[1]) for g in got}
            assert ("X", "u1") in kinds and ("Y", "i2") in kinds, got
        finally:
            speed.close()


# -- fidelity beyond the happy path (round-2 verdict #4) --------------------
# compressed inbound batches from foreign producers, coordinator movement
# mid-session, injected broker errors, nonzero throttle — the failure
# surfaces a hand-rolled happy-path fake can't catch by construction.

def _foreign_batch(records, codec: int, payload_transform) -> bytes:
    """A record batch as a FOREIGN producer would build it: compressed
    records payload (codec in attributes bits 0-2), CRC over
    attributes..end — structurally independent of encode_record_batch's
    uncompressed output."""
    from oryx_tpu.bus.kafkawire import Writer, crc32c

    body = Writer()
    for i, (key, value) in enumerate(records):
        rec = Writer()
        rec.i8(0)
        rec.varint(i * 17)  # nonzero timestamp deltas, like real producers
        rec.varint(i)
        if key is None:
            rec.varint(-1)
        else:
            rec.varint(len(key)).raw(key)
        rec.varint(len(value)).raw(value)
        rec.varint(0)
        rb = rec.done()
        body.varint(len(rb)).raw(rb)
    payload = payload_transform(body.done())
    crced = (
        Writer()
        .i16(codec)  # attributes: compression codec
        .i32(len(records) - 1)
        .i64(1_700_000_000_000)
        .i64(1_700_000_000_000 + (len(records) - 1) * 17)
        .i64(-1).i16(-1).i32(-1)
        .i32(len(records))
        .raw(payload)
        .done()
    )
    after_len = Writer().i32(-1).i8(2).u32(crc32c(crced)).raw(crced).done()
    return Writer().i64(0).i32(len(after_len)).raw(after_len).done()


def _snappy_compress_literals(data: bytes) -> bytes:
    """Minimal VALID snappy: uvarint length + literal-only elements (what
    a lazy compressor may legally emit)."""
    out = bytearray()
    n = len(data)
    while True:  # uvarint uncompressed length
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            break
    pos = 0
    while pos < len(data):
        chunk = data[pos:pos + 60]
        out.append((len(chunk) - 1) << 2)  # short literal tag
        out += chunk
        pos += len(chunk)
    return bytes(out)


@pytest.mark.parametrize(
    "codec_name", ["gzip", "snappy_raw", "snappy_xerial", "lz4", "zstd"]
)
def test_compressed_foreign_batches_decode(kafka, codec_name):
    import gzip as _gzip

    kafka.create_topic("FOREIGN-" + codec_name, 1)
    recs = [(b"k0", b"v0"), (None, "vé".encode()), (b"k2", b"x" * 500)]
    if codec_name == "gzip":
        batch = _foreign_batch(recs, 1, _gzip.compress)
    elif codec_name == "snappy_raw":
        batch = _foreign_batch(recs, 2, _snappy_compress_literals)
    elif codec_name == "snappy_xerial":
        def xerial(data: bytes) -> bytes:
            blk = _snappy_compress_literals(data)
            return (
                b"\x82SNAPPY\x00" + struct.pack(">ii", 1, 1)
                + struct.pack(">i", len(blk)) + blk
            )
        batch = _foreign_batch(recs, 2, xerial)
    elif codec_name == "lz4":
        # compressed with the CANONICAL system liblz4 — the same library
        # real producers link
        from oryx_tpu.bus.compress import CodecUnavailable, lz4f_compress

        try:
            batch = _foreign_batch(recs, 3, lz4f_compress)
        except CodecUnavailable:
            pytest.skip("liblz4 not on this host")
    else:
        from oryx_tpu.bus.compress import CodecUnavailable, zstd_compress

        try:
            batch = _foreign_batch(recs, 4, zstd_compress)
        except CodecUnavailable:
            pytest.skip("libzstd not on this host")
    # splice into the log like a foreign producer's append, after some
    # uncompressed records from OUR producer (mixed-codec log)
    kafka.send("FOREIGN-" + codec_name, "pre", "existing")
    server = kafka._test_server
    server.append_raw_batch("FOREIGN-" + codec_name, 0, batch)
    got = kafka.read("FOREIGN-" + codec_name, 0, 0, 100)
    assert got[0] == (0, "pre", "existing")
    assert got[1:] == [
        (1, "k0", "v0"), (2, None, "vé"), (3, "k2", "x" * 500),
    ]
    # offsets continue past the foreign batch for native appends
    kafka.send("FOREIGN-" + codec_name, "post", "after")
    got2 = kafka.read("FOREIGN-" + codec_name, 0, 4, 10)
    assert got2 == [(4, "post", "after")]


def test_coordinator_movement_mid_session():
    with LocalKafkaTestBroker() as node_a:
        node_b = LocalKafkaTestBroker(shared_from=node_a).start()
        try:
            broker = KafkaBroker([(node_a.host, node_a.port)])
            broker.create_topic("COORD", 1)
            broker.commit_offsets("g1", "COORD", {0: 5})
            assert broker.get_offsets("g1", "COORD") == {0: 5}
            # the coordinator moves to node B mid-session: node A now
            # points FindCoordinator at B and refuses commits itself
            node_a.move_coordinator(node_b.host, node_b.port)
            broker.commit_offsets("g1", "COORD", {0: 9})
            assert broker.get_offsets("g1", "COORD") == {0: 9}
            # the commit really landed in the (shared) group store via B
            assert node_b._group_offsets[("g1", "COORD")] == {0: 9}
            broker.close()
        finally:
            node_b.close()


def test_injected_coordinator_errors_retry(kafka):
    from oryx_tpu.bus.kafkawire import API_OFFSET_COMMIT, API_OFFSET_FETCH

    kafka.create_topic("CERR", 1)
    server = kafka._test_server
    # one NOT_COORDINATOR then success: the client must rediscover+retry
    server.inject_error(API_OFFSET_COMMIT, 16, times=1)
    kafka.commit_offsets("g2", "CERR", {0: 3})
    server.inject_error(API_OFFSET_FETCH, 15, times=1)  # COORD_NOT_AVAILABLE
    assert kafka.get_offsets("g2", "CERR") == {0: 3}
    # a persistent error surfaces instead of looping forever
    server.inject_error(API_OFFSET_COMMIT, 16, times=10)
    from oryx_tpu.bus.kafka import KafkaError

    with pytest.raises(KafkaError):
        kafka.commit_offsets("g2", "CERR", {0: 4})
    server._injected.clear()


def test_injected_produce_leader_error_retries(kafka):
    from oryx_tpu.bus.kafkawire import API_PRODUCE

    kafka.create_topic("PERR", 1)
    server = kafka._test_server
    server.inject_error(API_PRODUCE, 6, times=1)  # NOT_LEADER_FOR_PARTITION
    kafka.send("PERR", "k", "survived")  # refresh-metadata + retry path
    assert kafka.read("PERR", 0, 0, 10) == [(0, "k", "survived")]


def test_nonzero_throttle_is_tolerated(kafka):
    kafka.create_topic("THR", 1)
    server = kafka._test_server
    server.throttle_ms = 125
    kafka.send("THR", "k", "v")
    assert kafka.read("THR", 0, 0, 10) == [(0, "k", "v")]
    server.throttle_ms = 0


def test_snappy_decoder_property_roundtrip():
    """Property sweep: literal-only compression (any legal compressor's
    degenerate output) roundtrips arbitrary payloads, and hand-built
    copy elements (incl. overlapping RLE-style runs) decode per the
    snappy format spec."""
    import random

    from oryx_tpu.bus.kafkawire import _snappy_block_decompress, snappy_decompress

    rng = random.Random(42)
    for _ in range(50):
        n = rng.randrange(0, 5000)
        data = bytes(rng.randrange(256) for _ in range(min(n, 300))) * (
            1 if n <= 300 else n // 300
        )
        blk = _snappy_compress_literals(data)
        assert _snappy_block_decompress(blk) == data
        # xerial framing of the same block
        framed = (
            b"\x82SNAPPY\x00" + struct.pack(">ii", 1, 1)
            + struct.pack(">i", len(blk)) + blk
        )
        assert snappy_decompress(framed) == data

    # copy elements: 2-byte offset, 4-byte offset, 1-byte offset, overlap
    # "abcd" + copy(off=4, len=4) -> "abcdabcd"
    blk = bytes([8, 3 << 2]) + b"abcd" + bytes([((4 - 1) << 2) | 2]) + struct.pack("<H", 4)
    assert _snappy_block_decompress(blk) == b"abcdabcd"
    blk = bytes([8, 3 << 2]) + b"abcd" + bytes([((4 - 1) << 2) | 3]) + struct.pack("<I", 4)
    assert _snappy_block_decompress(blk) == b"abcdabcd"
    # 1-byte-offset copy: len = 4 + ((tag>>2)&7), off = (tag>>5)<<8 | byte
    blk = bytes([8, 3 << 2]) + b"abcd" + bytes([(0 << 5) | (0 << 2) | 1, 4])
    assert _snappy_block_decompress(blk) == b"abcdabcd"
    # overlapping run: "ab" + copy(off=1, len=6) -> "abbbbbbb"
    blk = bytes([8, 1 << 2]) + b"ab" + bytes([((6 - 1) << 2) | 2]) + struct.pack("<H", 1)
    assert _snappy_block_decompress(blk) == b"abbbbbbb"
    # corruption is an error, not silence
    import pytest as _pytest

    with _pytest.raises(ValueError):
        _snappy_block_decompress(bytes([200, 0 << 2]) + b"x")  # length mismatch
    with _pytest.raises(ValueError):
        # copy reaching before the start of output
        _snappy_block_decompress(bytes([4, ((4 - 1) << 2) | 2]) + struct.pack("<H", 9))


def test_lz4_zstd_bindings_edge_cases():
    """System-codec bindings: multi-block and big-block lz4 frames (the
    4MB-block case flushes buffered output with zero source consumed —
    a naive no-progress check rejects it), and hostile zstd declared
    sizes fail cleanly instead of attempting the allocation."""
    import ctypes
    import ctypes.util

    from oryx_tpu.bus.compress import (
        CodecUnavailable, lz4f_compress, lz4f_decompress,
        zstd_compress, zstd_decompress,
    )

    try:
        blob = bytes(range(256)) * 12_000  # ~3MB, multi-block at defaults
        assert lz4f_decompress(lz4f_compress(blob)) == blob
        assert zstd_decompress(zstd_compress(blob)) == blob
    except CodecUnavailable:
        pytest.skip("system codec libraries absent")

    # 4MB-block frame (blockSizeID 7), built with the canonical library
    lib = ctypes.CDLL(ctypes.util.find_library("lz4"))

    class Prefs(ctypes.Structure):
        _fields_ = [
            ("blockSizeID", ctypes.c_int), ("blockMode", ctypes.c_int),
            ("contentChecksumFlag", ctypes.c_int), ("frameType", ctypes.c_int),
            ("contentSize", ctypes.c_ulonglong), ("dictID", ctypes.c_uint),
            ("blockChecksumFlag", ctypes.c_int),
            ("compressionLevel", ctypes.c_int), ("autoFlush", ctypes.c_uint),
            ("favorDecSpeed", ctypes.c_uint), ("reserved", ctypes.c_uint * 3),
        ]

    prefs = Prefs()
    prefs.blockSizeID = 7
    data = b"xy" * 700_000
    lib.LZ4F_compressFrameBound.restype = ctypes.c_size_t
    cap = lib.LZ4F_compressFrameBound(len(data), ctypes.byref(prefs))
    dst = ctypes.create_string_buffer(cap)
    lib.LZ4F_compressFrame.restype = ctypes.c_size_t
    n = lib.LZ4F_compressFrame(dst, cap, data, len(data), ctypes.byref(prefs))
    assert lz4f_decompress(dst.raw[:n]) == data

    # hostile zstd: absurd declared content size -> ValueError, no alloc
    with pytest.raises(ValueError):
        zstd_decompress(b"\x28\xb5\x2f\xfd" + b"\x64" + b"\xff" * 8)


def test_native_crc32c_tier(monkeypatch):
    """Without google_crc32c, crc32c resolves to the native SSE4.2
    implementation (oryxbus_crc32c) and agrees with the pure-python
    reference incl. chained-crc semantics."""
    import builtins
    import sys as _sys

    from oryx_tpu.bus import kafkawire as kw

    real_import = builtins.__import__

    def no_gcrc(name, *a, **k):
        if name == "google_crc32c":
            raise ImportError("masked for test")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_gcrc)
    monkeypatch.delitem(_sys.modules, "google_crc32c", raising=False)
    fn = kw._resolve_crc32c()
    if fn.__name__ == "_crc32c_py":
        pytest.skip("native library unavailable on this host")
    assert fn.__name__ == "crc32c_native"
    assert fn(b"123456789") == 0xE3069283
    blob = os.urandom(5000)
    assert fn(blob) == kw._crc32c_py(blob)
    assert fn(blob[100:], fn(blob[:100])) == kw._crc32c_py(blob)
