"""Hot-path latency attribution (common/perfattr.py): phase ledgers,
idle-gap classification, compile telemetry + storm events, the latency
budget surfaces, and the `oryx perf` report.

Includes the ISSUE 17 tier-1 acceptance scenario: requests driven through
a real ServingLayer must produce phase-budget samples summing to >= 95%
of the measured request wall-clock with zero unattributed idle-gap share
in the steady-state window, and a forced latency fast-burn must leave a
harvestable profile-capture event (with the phase-budget payload) in the
on-disk flight ring.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from oryx_tpu.common.perfattr import (
    PHASES,
    PerfAttr,
    PhaseLedger,
    classify_idle_gap,
    current_ledger,
    get_perfattr,
    swap_ledger,
)


# ---- phase ledger ----------------------------------------------------------


def test_phase_ledger_add_items_total():
    led = PhaseLedger()
    led.add("parse", 0.002, start=1.0)
    led.add("device", 0.01)          # no start: still counted, no span
    led.add("write", -0.5)           # clock skew: dropped
    led.add("auth", float("nan"))    # NaN: dropped
    items = led.items()
    assert [p for p, _, _ in items] == ["parse", "device"]
    assert items[0][1] == 1.0
    assert items[1][1] == -1.0       # sentinel for "no start known"
    assert led.total() == pytest.approx(0.012)


def test_swap_ledger_is_thread_local():
    led = PhaseLedger()
    assert swap_ledger(led) is None
    assert current_ledger() is led
    seen = []

    def other():
        seen.append(current_ledger())

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert seen == [None]            # the mirror never leaks across threads
    assert swap_ledger(None) is led
    assert current_ledger() is None


# ---- idle-gap classification -----------------------------------------------


def test_classify_idle_gap_measured_causes():
    causes = classify_idle_gap(1.0, wait_s=0.9, serialize_s=0.1)
    assert causes == {
        "empty_queue": pytest.approx(0.9),
        "host_serialize": pytest.approx(0.1),
    }
    # cap order: wait first, then down, then serialize, each bounded by
    # what the gap can still hold
    causes = classify_idle_gap(1.0, wait_s=2.0, serialize_s=5.0, down_s=5.0)
    assert causes == {"empty_queue": pytest.approx(1.0)}
    causes = classify_idle_gap(1.0, down_s=0.7, serialize_s=0.9)
    assert causes["failover_backoff"] == pytest.approx(0.7)
    assert causes["host_serialize"] == pytest.approx(0.3)


def test_classify_idle_gap_residue_fold_and_unattributed():
    # small residue (<= max(2ms, 10%)) folds into host_serialize
    causes = classify_idle_gap(0.010, wait_s=0.0095)
    assert set(causes) == {"empty_queue", "host_serialize"}
    assert causes["host_serialize"] == pytest.approx(0.0005)
    # large residue is reported honestly
    causes = classify_idle_gap(1.0, wait_s=0.2)
    assert causes["unattributed"] == pytest.approx(0.8)
    # zero / negative gaps (pipelined dispatches) classify to nothing
    assert classify_idle_gap(0.0) == {}
    assert classify_idle_gap(-0.5) == {}


# ---- budget window + flush idempotence -------------------------------------


def _ledger(phases: dict[str, float]) -> PhaseLedger:
    led = PhaseLedger()
    t = led.t0
    for phase, s in phases.items():
        led.add(phase, s, start=t)
        t += s
    return led


def test_observe_request_is_idempotent_per_ledger():
    pa = PerfAttr(window_s=300.0)
    led = _ledger({"parse": 0.001, "device": 0.01})
    pa.observe_request(led)
    pa.observe_request(led)          # the Deferred + sync paths both flush
    b = pa.budget()
    assert b["phases"]["parse"]["count"] == 1
    assert b["phases"]["device"]["count"] == 1
    assert b["total_phase_seconds"] == pytest.approx(0.011, abs=1e-4)


def test_budget_percentiles_shares_and_gap_ranking():
    pa = PerfAttr(window_s=300.0)
    for ms in (1, 2, 3, 4, 100):
        pa.observe_request(_ledger({"device": ms / 1e3, "parse": 0.001}))
    pa.record_idle_gap("empty_queue", 0.9)
    pa.record_idle_gap("host_serialize", 0.1)
    pa.record_idle_gap("bogus", -1.0)     # non-positive: dropped
    b = pa.budget()
    dev = b["phases"]["device"]
    assert dev["count"] == 5
    assert dev["p50_ms"] == pytest.approx(3.0)
    assert dev["p99_ms"] == pytest.approx(100.0)
    total = 0.110 + 5 * 0.001
    assert dev["share"] == pytest.approx(0.110 / total, abs=1e-3)
    # phase ordering follows the catalog; shares sum to ~1
    assert list(b["phases"]) == ["parse", "device"]
    assert sum(p["share"] for p in b["phases"].values()) == pytest.approx(
        1.0, abs=0.01
    )
    gaps = b["idle_gaps"]
    assert list(gaps) == ["empty_queue", "host_serialize"]  # ranked
    assert gaps["empty_queue"]["share"] == pytest.approx(0.9)
    assert "bogus" not in gaps


def test_budget_window_expires_old_stamps():
    pa = PerfAttr(window_s=0.05)
    pa.observe_request(_ledger({"device": 0.01}))
    pa.record_idle_gap("empty_queue", 0.5)
    time.sleep(0.08)
    b = pa.budget()
    assert b["phases"] == {}
    assert b["idle_gaps"] == {}


def test_disabled_perfattr_still_feeds_histograms_not_windows():
    pa = PerfAttr(window_s=300.0)
    pa.enabled = False
    pa.observe_request(_ledger({"device": 0.01}))
    pa.record_idle_gap("empty_queue", 0.5)
    assert pa.budget()["phases"] == {}   # derived window off...
    from oryx_tpu.common.metrics import get_registry

    text = get_registry().render_prometheus()
    # ...but the raw families exist regardless (always-on contract)
    assert "oryx_request_phase_seconds" in text
    assert "oryx_device_idle_gap_seconds" in text


def test_phase_spans_replay_into_the_trace_waterfall():
    from oryx_tpu.common.tracing import get_tracer

    tr = get_tracer()
    tr.configure(enabled=True, capacity=256)
    try:
        pa = PerfAttr(window_s=300.0)
        root = tr.start("http.request")
        led = PhaseLedger(trace=root)
        t = time.monotonic() - 0.1
        led.add("parse", 0.001, start=t)
        led.add("device", 0.02, start=t + 0.001)
        led.add("drain", 0.005)          # no start: histogram only, no span
        pa.observe_request(led)
        tr.finish(root)
        spans = {s.name: s for s in tr.snapshot()}
        assert "phase.parse" in spans and "phase.device" in spans
        assert spans["phase.device"].parent_id == root.span_id
        assert "phase.drain" not in spans
        assert led.trace_id == root.trace_id
    finally:
        tr.configure(enabled=False, capacity=2048)


# ---- compile telemetry + storm ---------------------------------------------


def _flight_to(tmp_path):
    """Point the global flight recorder at tmp and disarm stale episode
    rate-limits so this test observes ITS events."""
    from oryx_tpu.common import flightrec

    rec = flightrec.get_flightrec()
    rec.dir = str(tmp_path)
    rec.enabled = True
    with rec._lock:
        rec._last_episode.pop("compile-storm", None)
    return rec


def test_compile_storm_fires_flight_event(tmp_path):
    from oryx_tpu.common import flightrec

    _flight_to(tmp_path)
    pa = PerfAttr(window_s=300.0)
    pa.storm_threshold = 3
    pa.storm_window_s = 60.0
    pa.record_compile("serving", 0.2)
    pa.record_compile("serving", 0.3)
    events = [
        e for e in flightrec.read_events(str(tmp_path))
        if e.get("kind") == "compile-storm"
    ]
    assert not events                    # below threshold: quiet
    pa.record_compile("serving", 0.4)    # third within the window: storm
    events = [
        e for e in flightrec.read_events(str(tmp_path))
        if e.get("kind") == "compile-storm"
    ]
    assert events, "threshold recompiles recorded no compile-storm"
    ev = events[-1]
    assert ev["compiles"] >= 3
    assert ev["dispatch_kind"] == "serving"
    assert ev["window_s"] == 60.0
    assert ev["last_compile_s"] == pytest.approx(0.4)


def _counter_total(name: str, **labels) -> float:
    from oryx_tpu.common.metrics import get_registry

    total = 0.0
    for key, v in get_registry().counter(name).series().items():
        if all(dict(key).get(k) == val for k, val in labels.items()):
            total += v
    return total


def test_batcher_new_k_bucket_increments_compile_telemetry(tmp_path):
    """Tier-1 (ISSUE 17): a shape-signature change (new k-bucket) must
    increment the compile counter/histogram, charge a compile_stall idle
    slice, and land a batcher.compile_stall span in the waterfall."""
    from oryx_tpu.common.tracing import get_tracer
    from oryx_tpu.serving.batcher import TopKBatcher, k_bucket

    tr = get_tracer()
    tr.configure(enabled=True, capacity=1024)
    try:
        rng = np.random.default_rng(7)
        y = jnp.asarray(rng.normal(size=(64, 8)), dtype=jnp.float32)
        rows = y.shape[0]
        kb_lo = min(k_bucket(5), rows)
        kb_hi = min(k_bucket(40), rows)
        assert kb_lo != kb_hi  # distinct shape signatures by construction
        before = _counter_total("oryx_xla_compiles_total", kind="serving")
        b = TopKBatcher()
        try:
            vec = rng.normal(size=8).astype(np.float32)
            b.submit(vec, 5, y)      # first signature (k-bucket kb_lo)
            b.submit(vec, 40, y)     # NEW signature (k-bucket kb_hi)
        finally:
            b.close()
        after = _counter_total("oryx_xla_compiles_total", kind="serving")
        assert after - before == 2.0
        stall_spans = [
            s for s in tr.snapshot() if s.name == "batcher.compile_stall"
        ]
        assert len(stall_spans) >= 2
        assert {s.attrs["k"] for s in stall_spans} >= {kb_lo, kb_hi}
        # the stall also landed in the device idle account
        gaps = get_perfattr().budget()["idle_gaps"]
        assert gaps.get("compile_stall", {}).get("seconds", 0.0) > 0.0
    finally:
        tr.configure(enabled=False, capacity=2048)


# ---- serving end-to-end: the attribution contract --------------------------


def _als_serving_config(bus: str, tmp_path, **extra):
    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.common.config import load_config

    broker = get_broker(bus)
    for t in ("OryxInput", "OryxUpdate"):
        if not broker.topic_exists(t):
            broker.create_topic(t, 1)
    overlay = {
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.serving.api.port": 0,
        "oryx.monitoring.flight.dir": str(tmp_path / "flight"),
        "oryx.monitoring.perfattr.window-sec": 300,
        "oryx.serving.model-manager-class":
            "oryx_tpu.apps.als.serving.ALSServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.als",
        ],
    }
    overlay.update(extra)
    return load_config(overlay=overlay)


def _als_manager(cfg, n_users=32, n_items=64, features=8):
    from oryx_tpu.apps.als.serving import (
        ALSServingModel,
        ALSServingModelManager,
    )
    from oryx_tpu.apps.als.state import ALSState
    from oryx_tpu.common.rng import RandomManager

    rng = RandomManager.get_random()
    state = ALSState(features, implicit=True)
    state.x.bulk_set(
        [f"u{i}" for i in range(n_users)],
        rng.standard_normal((n_users, features)).astype("float32"),
    )
    state.y.bulk_set(
        [f"i{i}" for i in range(n_items)],
        rng.standard_normal((n_items, features)).astype("float32"),
    )
    state.set_expected(state.x.ids(), state.y.ids())
    manager = ALSServingModelManager(cfg)
    manager.model = ALSServingModel(state)
    return manager


def _phase_metric_sums(text: str) -> dict[str, dict[str, float]]:
    """family -> {label value -> _sum} for the perfattr histograms."""
    from oryx_tpu.cli import _parse_metric_sample

    out: dict[str, dict[str, float]] = {
        "oryx_request_phase_seconds": {},
        "oryx_device_idle_gap_seconds": {},
        "oryx_serving_request_seconds": {},
    }
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        parsed = _parse_metric_sample(line)
        if parsed is None:
            continue
        name, labels, value = parsed
        for family, acc in out.items():
            if name == family + "_sum":
                key = labels.get("phase") or labels.get("cause") or (
                    labels.get("method", "")
                )
                acc[key] = acc.get(key, 0.0) + value
    return out


def test_e2e_attribution_covers_request_wall_clock(tmp_path):
    """The acceptance contract: after warmup, phase stamps must tile the
    measured request wall-clock (>= 95% of the serving-request histogram
    delta) and every idle gap must classify without unattributed share;
    /healthz must advertise the latency budget."""
    from e2e_common import http_request

    from oryx_tpu.common.metrics import get_registry
    from oryx_tpu.serving.server import ServingLayer

    cfg = _als_serving_config("mem://perfattr-e2e", tmp_path)
    manager = _als_manager(cfg)
    with ServingLayer(cfg, model_manager=manager) as sl:
        base = f"http://127.0.0.1:{sl.port}"
        # warmup: backend init + first-shape compiles + the one-time
        # startup idle gap are NOT steady state
        for i in range(5):
            status, _ = http_request("GET", f"{base}/recommend/u{i}?howMany=4")
            assert status == 200
        time.sleep(0.3)              # drain in-flight flushes
        before = _phase_metric_sums(get_registry().render_prometheus())

        n = 40
        for i in range(n):
            status, _ = http_request(
                "GET", f"{base}/recommend/u{i % 16}?howMany=6"
            )
            assert status == 200
        time.sleep(0.3)
        after = _phase_metric_sums(get_registry().render_prometheus())

        def delta(family: str) -> dict[str, float]:
            return {
                k: after[family].get(k, 0.0) - before[family].get(k, 0.0)
                for k in after[family]
            }

        phase_d = delta("oryx_request_phase_seconds")
        serving_d = sum(delta("oryx_serving_request_seconds").values())
        attributed = sum(phase_d.values())
        assert serving_d > 0.0
        assert attributed >= 0.95 * serving_d, (
            f"phases covered {attributed:.4f}s of {serving_d:.4f}s "
            f"({attributed / serving_d:.1%}): {phase_d}"
        )
        # the hot phases all landed samples
        assert phase_d.get("queue_wait", 0.0) > 0.0
        assert phase_d.get("device", 0.0) + phase_d.get(
            "host_fallback", 0.0
        ) > 0.0
        assert phase_d.get("serialize", 0.0) > 0.0
        # unknown phases never appear in THIS window: the hot path only
        # stamps catalog names (other tests may have seeded odd labels
        # into the process-global family, so zero-delta keys are ignored)
        assert {k for k, v in phase_d.items() if v > 0.0} <= set(PHASES)

        # steady state: every idle gap classified, zero unattributed
        gap_d = delta("oryx_device_idle_gap_seconds")
        classified = sum(v for k, v in gap_d.items() if k != "unattributed")
        assert classified > 0.0
        assert gap_d.get("unattributed", 0.0) == pytest.approx(0.0, abs=1e-9)

        # /healthz advertises the budget the fleet front federates
        status, body = http_request("GET", f"{base}/healthz")
        assert status == 200
        lb = json.loads(body).get("latency_budget")
        assert lb and lb["phases"], body[:400]
        assert "device" in lb["phases"] or "host_fallback" in lb["phases"]
        for row in lb["phases"].values():
            assert set(row) == {"count", "p50_ms", "p99_ms", "share"}

        # the `oryx perf` report renders from the same exposition
        from oryx_tpu.cli import render_perf_report

        report = render_perf_report(get_registry().render_prometheus())
        assert "latency budget (oryx_request_phase_seconds)" in report
        assert "queue_wait" in report
        assert "device idle gaps (oryx_device_idle_gap_seconds)" in report


def test_e2e_forced_fast_burn_leaves_profile_capture(tmp_path):
    """A latency fast-burn must leave a harvestable profile-capture
    event (with the phase-budget payload) in the on-disk flight ring —
    the profile corpse contract."""
    from e2e_common import http_request

    from oryx_tpu.common import flightrec
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.perfattr import configure_perfattr
    from oryx_tpu.serving.server import ServingLayer

    cfg = _als_serving_config(
        "mem://perfattr-burn", tmp_path, **{
            # every request is "bad": an impossible latency objective
            "oryx.monitoring.slo.latency.threshold-sec": 1e-9,
            "oryx.monitoring.perfattr.burn-capture.burn-threshold": 1,
            "oryx.monitoring.perfattr.burn-capture.check-interval-sec": 0,
            "oryx.monitoring.perfattr.burn-capture.capture-sec": 0.05,
            "oryx.monitoring.perfattr.burn-capture.min-interval-sec": 600,
        }
    )
    manager = _als_manager(cfg)
    flight_dir = str(tmp_path / "flight")
    pa = get_perfattr()
    try:
        with ServingLayer(cfg, model_manager=manager) as sl:
            # a prior test may have armed the gates; this test owns them
            pa._next_burn_check = 0.0
            pa._burn_cooldown_until = 0.0
            base = f"http://127.0.0.1:{sl.port}"
            deadline = time.monotonic() + 15.0
            events = []
            while time.monotonic() < deadline:
                status, _ = http_request(
                    "GET", f"{base}/recommend/u0?howMany=4"
                )
                assert status == 200
                # > the SLO sampler's min gap, so the tracker's burn ring
                # accumulates a baseline then a hot sample
                time.sleep(0.06)
                events = [
                    e for e in flightrec.read_events(flight_dir)
                    if e.get("kind") == "profile-capture"
                ]
                if events:
                    break
            assert events, "fast burn left no profile-capture event"
            ev = events[-1]
            assert ev["trigger"] == "latency-fast-burn"
            assert ev["burn_rate"] >= 1.0
            assert ev["budget"]["phases"], ev
            assert "profile" in ev
    finally:
        # restore process-global perfattr defaults for later tests
        configure_perfattr(load_config())


# ---- fleet federation -------------------------------------------------------


def test_merge_latency_budgets():
    from oryx_tpu.fleet.observe import merge_latency_budgets

    b1 = {
        "window_s": 60,
        "phases": {
            "device": {"count": 10, "p50_ms": 2.0, "p99_ms": 8.0,
                       "share": 0.8},
            "parse": {"count": 10, "p50_ms": 0.5, "p99_ms": 1.0,
                      "share": 0.2},
        },
        "idle_gaps": {"empty_queue": {"seconds": 3.0, "share": 1.0}},
    }
    b2 = {
        "window_s": 60,
        "phases": {
            "device": {"count": 30, "p50_ms": 4.0, "p99_ms": 16.0,
                       "share": 1.0},
        },
        "idle_gaps": {
            "empty_queue": {"seconds": 1.0, "share": 0.5},
            "host_serialize": {"seconds": 1.0, "share": 0.5},
        },
    }
    merged = merge_latency_budgets([b1, b2, None, "junk"])
    assert merged["replicas"] == 2
    dev = merged["phases"]["device"]
    assert dev["count"] == 40
    # count-weighted mean of the replica percentiles
    assert dev["p50_ms"] == pytest.approx((10 * 2.0 + 30 * 4.0) / 40)
    assert dev["p99_ms"] == pytest.approx((10 * 8.0 + 30 * 16.0) / 40)
    assert merged["phases"]["parse"]["count"] == 10
    # shares recomputed from merged mass, ~sum to 1
    assert sum(
        p["share"] for p in merged["phases"].values()
    ) == pytest.approx(1.0, abs=0.01)
    gaps = merged["idle_gaps"]
    assert gaps["empty_queue"]["seconds"] == pytest.approx(4.0)
    assert list(gaps) == ["empty_queue", "host_serialize"]  # ranked
    assert merge_latency_budgets([]) == {
        "window_s": 0.0, "replicas": 0, "phases": {}, "idle_gaps": {},
    }


# ---- `oryx perf` renderer ---------------------------------------------------


SAMPLE_EXPOSITION = """\
# HELP oryx_request_phase_seconds per-request phase time
# TYPE oryx_request_phase_seconds histogram
oryx_request_phase_seconds_bucket{phase="device",le="0.001"} 0
oryx_request_phase_seconds_bucket{phase="device",le="0.01"} 8
oryx_request_phase_seconds_bucket{phase="device",le="+Inf"} 10
oryx_request_phase_seconds_sum{phase="device"} 0.2
oryx_request_phase_seconds_count{phase="device"} 10
oryx_request_phase_seconds_bucket{phase="parse",le="0.001"} 10
oryx_request_phase_seconds_bucket{phase="parse",le="+Inf"} 10
oryx_request_phase_seconds_sum{phase="parse"} 0.005
oryx_request_phase_seconds_count{phase="parse"} 10
# TYPE oryx_device_idle_gap_seconds histogram
oryx_device_idle_gap_seconds_sum{cause="empty_queue"} 9.0
oryx_device_idle_gap_seconds_count{cause="empty_queue"} 12
oryx_device_idle_gap_seconds_sum{cause="compile_stall"} 1.0
oryx_device_idle_gap_seconds_count{cause="compile_stall"} 2
# TYPE oryx_xla_compiles_total counter
oryx_xla_compiles_total{kind="serving"} 2
# TYPE oryx_xla_compile_seconds histogram
oryx_xla_compile_seconds_sum{kind="serving"} 1.0
oryx_xla_compile_seconds_count{kind="serving"} 2
"""


def test_render_perf_report_from_exposition():
    from oryx_tpu.cli import render_perf_report

    report = render_perf_report(SAMPLE_EXPOSITION)
    lines = report.splitlines()
    # device ranks above parse (share of summed seconds)
    dev_i = next(i for i, ln in enumerate(lines) if "device " in ln)
    parse_i = next(i for i, ln in enumerate(lines) if "parse" in ln)
    assert dev_i < parse_i
    dev_line = lines[dev_i]
    assert "10" in dev_line and "10ms" in dev_line       # p50 bucket bound
    assert "97.6%" in dev_line                           # 0.2 / 0.205
    # p99 beyond the largest finite bound renders as an honest ">"
    assert ">10ms" in dev_line
    assert "empty_queue" in report and "90.0%" in report
    assert "compile_stall" in report
    assert "xla compiles (oryx_xla_compiles_total)" in report
    assert "serving" in report
    # empty exposition renders placeholders, not a crash
    empty = render_perf_report("")
    assert "(no phase samples yet)" in empty
    assert "(no compiles recorded yet)" in empty


def test_parse_metric_sample_edges():
    from oryx_tpu.cli import _parse_metric_sample

    assert _parse_metric_sample("foo 1.5") == ("foo", {}, 1.5)
    name, labels, v = _parse_metric_sample(
        'h_bucket{a="x",le="+Inf"} 7 # {trace_id="abc"} 0.2 123'
    )
    assert name == "h_bucket" and labels == {"a": "x", "le": "+Inf"}
    assert v == 7.0
    assert _parse_metric_sample("# HELP foo bar") is None
    assert _parse_metric_sample("foo{a=") is None
    assert _parse_metric_sample("foo nan_is_fine_but_words_are_not") is None


# ---- bench phase heartbeats -------------------------------------------------


def test_bench_flight_phase_records_prev_duration():
    import bench

    class Rec:
        def __init__(self):
            self.rows = []

        def record(self, **fields):
            self.rows.append(fields)

    rec = Rec()
    bench._STAGE_PHASE.pop("t-stage", None)
    bench._flight_phase(rec, "t-stage", "alpha")
    time.sleep(0.01)
    bench._flight_phase(rec, "t-stage", "beta")
    assert rec.rows[0] == {
        "kind": "bench-stage", "stage": "t-stage", "phase": "alpha",
    }
    second = rec.rows[1]
    assert second["phase"] == "beta"
    assert second["prev_phase"] == "alpha"
    assert second["prev_s"] >= 0.01
    bench._STAGE_PHASE.pop("t-stage", None)
