"""RDF speed tier: per-micro-batch terminal-node statistics.

Mirrors RDFSpeedModelManager (app/oryx-app .../speed/rdf/
RDFSpeedModelManager.java:68-148): "UP" is ignored (hearing our own
updates), MODEL(-REF) replaces the local forest, and build_updates routes
every example down every tree — one vectorized [T,N] routing pass instead
of the reference's per-example flatMap — groups targets by (tree,
terminal node), and emits ("UP", message) pairs whose JSON payloads are
byte-compatible with the reference wire format:
  classification: [treeID, nodeID, {targetEncoding: count}]
  regression:     [treeID, nodeID, mean, count]
(imported PMML forests emit label-keyed counts instead — the key space
their serving counterpart folds by).
"""

from __future__ import annotations

import json
import logging

import numpy as np

from oryx_tpu.api import AbstractSpeedModelManager
from oryx_tpu.common.artifact import read_artifact_from_update
from oryx_tpu.common.config import Config
from oryx_tpu.common.text import parse_input_line
from oryx_tpu.ops.rdf import heap_to_node_id
from oryx_tpu.apps.rdf.common import RDFModel, artifact_to_model
from oryx_tpu.apps.schema import InputSchema

log = logging.getLogger(__name__)


class RDFSpeedModelManager(AbstractSpeedModelManager):
    def __init__(self, config: Config):
        self.config = config
        self.schema = InputSchema(config)
        self.model: RDFModel | None = None
        self.pmml_forest = None  # imported reference forest (common/pmml.py)

    def consume_key_message(self, key: str | None, message: str) -> None:
        if key == "UP":
            return  # hearing our own updates
        if key in ("MODEL", "MODEL-REF"):
            art = read_artifact_from_update(key, message)
            if art.app == "rdf-pmml":
                from oryx_tpu.common.pmml import PredicateForest

                self.pmml_forest = PredicateForest.from_artifact(art)
                self.model = None
                log.info(
                    "imported PMML model loaded: %d trees", len(self.pmml_forest.trees)
                )
            else:
                self.model = artifact_to_model(art, self.schema)
                self.pmml_forest = None
                log.info(
                    "new model loaded: %d trees, depth %d",
                    self.model.forest.num_trees,
                    self.model.forest.max_depth,
                )
        else:
            raise ValueError(f"bad key: {key}")

    def _build_updates_pmml(self, forest, new_data):
        """Route each example through the imported predicate forest and emit
        label-keyed per-(tree, node) stats — the key space its serving-side
        counterpart (PMMLForestServingModel) folds by."""
        from oryx_tpu.apps.rdf.common import tokens_to_features

        stats: dict[tuple[int, str], list] = {}
        for km in new_data:
            try:
                tokens = parse_input_line(km.message)
            except ValueError:
                continue
            features, target = tokens_to_features(self.schema, tokens)
            if target is None:
                continue
            for t, nid in enumerate(forest.terminal_ids(features)):
                if nid is not None:
                    stats.setdefault((t, nid), []).append(target)
        out = []
        for (t, nid), targets in sorted(stats.items()):
            if forest.is_classification:
                counts: dict[str, int] = {}
                for v in targets:
                    counts[v] = counts.get(v, 0) + 1
                out.append(("UP", json.dumps([t, nid, counts])))
            else:
                # tolerate unparseable targets like the native path's
                # NaN-drop (keep = ~np.isnan(y)) — one bad record must not
                # poison the micro-batch retry loop
                values = []
                for v in targets:
                    try:
                        values.append(float(v))
                    except ValueError:
                        continue
                if values:
                    out.append(
                        ("UP", json.dumps([t, nid, float(np.mean(values)), len(values)]))
                    )
        return out

    def build_updates(self, new_data):
        # snapshot both models once: the update-listener thread swaps them
        pmml_forest = self.pmml_forest
        if pmml_forest is not None:
            return self._build_updates_pmml(pmml_forest, new_data)
        model = self.model
        if model is None:
            return []
        rows = []
        for km in new_data:
            try:
                rows.append(parse_input_line(km.message))
            except ValueError:
                continue
        if not rows:
            return []
        x, y = model.rows_to_matrix(rows)
        keep = ~np.isnan(y)
        x, y = x[keep], y[keep]
        if len(y) == 0:
            return []
        binned = model.bin_matrix(x)
        leaves = model.terminal_nodes(binned)  # [T, N]
        classification = model.forest.is_classification

        out = []
        for t in range(leaves.shape[0]):
            for slot in np.unique(leaves[t]):
                targets = y[leaves[t] == slot]
                nid = heap_to_node_id(int(slot))
                if classification:
                    codes, counts = np.unique(targets.astype(np.int64), return_counts=True)
                    payload = {str(int(c)): int(n) for c, n in zip(codes, counts)}
                    out.append(("UP", json.dumps([t, nid, payload])))
                else:
                    out.append(
                        (
                            "UP",
                            json.dumps(
                                [t, nid, float(np.mean(targets)), int(len(targets))]
                            ),
                        )
                    )
        return out
