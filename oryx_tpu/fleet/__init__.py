"""Replicated serving fleet: supervisor, consistent-hash front, shared
model distribution.

The single-host serving story scales event loops (PR 1) and replica
processes on one port (``oryx.serving.api.processes``); this package is
the N-hosts story the lambda contract makes natural — serving instances
are stateless consumers of the update topic (PAPER.md), so a fleet is N
independent serving processes behind a thin L7 front:

- :mod:`oryx_tpu.fleet.supervisor` launches and monitors N serving
  replicas on distinct ports with per-replica config overlays.
- :mod:`oryx_tpu.fleet.front` is the router: round-robin or
  consistent-hash-by-user placement, health-driven ejection from the
  replicas' ``GET /healthz`` degraded states, and Retry-After-aware
  retry of shed requests on a different replica.
- :mod:`oryx_tpu.fleet.ring` is the hash ring behind the hash policy.
- :mod:`oryx_tpu.fleet.control` closes the loop: canary rollout with
  quality-gated promotion and pointer-swap rollback, plus SLO-burn
  autoscaling with connection draining on scale-down.

Model distribution is amortized across co-hosted replicas by the shared
artifact relay cache (``common/artifact.py``): MODEL-CHUNK reassembly
happens once per host, measured by
``oryx_fleet_distribution_bytes{mode=shared|per-replica}``.
"""

from oryx_tpu.fleet.ring import HashRing
from oryx_tpu.fleet.control import FleetController
from oryx_tpu.fleet.front import FleetFront, ReplicaInfo
from oryx_tpu.fleet.supervisor import FleetSupervisor, replica_overlays

__all__ = [
    "FleetController",
    "FleetFront",
    "FleetSupervisor",
    "HashRing",
    "ReplicaInfo",
    "replica_overlays",
]
