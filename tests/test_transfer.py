"""Staged host->device transfers (ops/transfer.py): equivalence with the
direct upload across sizes, dtypes, and chunk boundaries."""

import numpy as np
import jax.numpy as jnp

from oryx_tpu.ops.transfer import staged_device_put


def test_small_array_direct_path():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = staged_device_put(a)
    np.testing.assert_array_equal(np.asarray(out), a)


def test_chunked_equals_direct():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((1000, 16)).astype(np.float32)
    out = staged_device_put(a, chunk_bytes=16 * 4 * 100)  # 100-row chunks
    assert out.shape == a.shape
    np.testing.assert_array_equal(np.asarray(out), a)


def test_chunked_with_dtype_cast():
    rng = np.random.default_rng(1)
    a = rng.standard_normal((257, 8)).astype(np.float32)  # ragged last chunk
    out = staged_device_put(a, dtype=jnp.bfloat16, chunk_bytes=8 * 2 * 64)
    ref = jnp.asarray(a, dtype=jnp.bfloat16)
    assert out.dtype == ref.dtype
    np.testing.assert_array_equal(
        np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32)
    )


def test_1d_and_scalar():
    a = np.arange(100000, dtype=np.int32)
    out = staged_device_put(a, chunk_bytes=1024)
    np.testing.assert_array_equal(np.asarray(out), a)
    s = staged_device_put(np.float32(3.5))
    assert float(s) == 3.5
