"""Repo tooling package (enables ``python -m tools.oryxlint`` and friends).

The scripts in this directory remain directly runnable
(``python tools/check_config.py``); this package init exists so the
oryxlint static-analysis framework can be invoked as a module and
imported by tests.
"""
