"""Exit-discipline contracts for the bench harness (round-3 verdict #1).

The driver's capture can kill bench.py at any moment (BENCH_r03.json:
rc 124, standing record "interim": true). These pin the fix: SIGTERM
finalizes the standing best artifact as a FINAL (non-interim) line and
exits 0; the wedge classifier and suite budget derive from one named
primary-cap constant; and the unmeasured Spark denominator carries an
explicitly-labeled bound instead of a bare null.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def test_suite_budget_derives_from_primary_cap():
    assert bench._SUITE_BUDGET == bench._PRIMARY_CAP + sum(
        s[1] for s in bench._SUITE_STAGES
    )


def test_default_budget_under_driver_timeout():
    # bench's BUILT-IN default must leave the driver's capture timeout
    # room to see a clean exit 0 (45 min ceiling); the env var can still
    # override per-run for operator-attended long waits
    assert bench._DEFAULT_BUDGET_S <= 2700


def test_baseline_bound_attached_and_labeled():
    result: dict = {}
    bench._attach_baseline_bound(result, build_s=100.0, nnz=25_000_000)
    bound = result["spark_baseline_bound"]
    # the analytic floor: 10 it x 2 sides x nnz x (2f^2 + 2f) / 200 GF/s
    expect_floor = 10 * 2.0 * 25e6 * (2 * 50**2 + 2 * 50) / 200e9
    assert bound["analytic_floor_seconds"] == round(expect_floor, 1)
    assert bound["speedup_vs_mllib_floor"] == round(expect_floor / 100.0, 2)
    # anchor scales linearly in interactions from the 25M range
    assert bound["literature_anchor_seconds"] == [300.0, 1800.0]
    assert bound["speedup_vs_mllib_anchor_range"] == [3.0, 18.0]
    # both must say what they are
    assert "anchor, not a measurement" in bound["literature_anchor_basis"]
    assert "optimistic" in bound["analytic_floor_basis"]
    assert "spark_baseline.py" in bound["command"]


def test_baseline_bound_without_build():
    result: dict = {}
    bench._attach_baseline_bound(result, build_s=None, nnz=1_000_000)
    bound = result["spark_baseline_bound"]
    assert "speedup_vs_mllib_floor" not in bound
    assert bound["literature_anchor_seconds"] == [12.0, 72.0]


def test_select_final_prefers_accel_partial_over_complete_cpu():
    # a 3-key wedged TPU partial must beat a bigger complete CPU anchor
    tpu = {"metric": "m", "value": 1.0, "platform": "tpu"}
    cpu = {
        "metric": "m_cpu", "value": 2.0, "platform": "cpu",
        "kernel_qps": 1.0, "als_build_seconds": 1.0, "scaling": [],
        "suite_complete": True,
    }
    best, is_cpu = bench._select_final(dict(tpu), None, dict(cpu))
    assert not is_cpu
    assert best["platform"] == "tpu"
    assert best["partial"] is True  # wedged mid-run: labeled


def test_select_final_complete_accel_not_marked_partial():
    tpu = {"metric": "m", "platform": "tpu", "suite_complete": True}
    best, is_cpu = bench._select_final(None, dict(tpu), None)
    assert not is_cpu
    assert "partial" not in best
    assert "suite_complete" not in best


def test_select_final_cpu_anchor_when_no_accel():
    # killed mid-CPU-suite (no suite_complete): labeled partial
    cpu = {"metric": "m_cpu", "platform": "cpu", "interim": True}
    best, is_cpu = bench._select_final(None, None, dict(cpu))
    assert is_cpu
    assert "interim" not in best
    assert best["partial"] is True
    # a complete CPU anchor is not partial
    done = {"metric": "m_cpu", "platform": "cpu", "suite_complete": True}
    best2, _ = bench._select_final(None, None, dict(done))
    assert "partial" not in best2 and "suite_complete" not in best2
    assert bench._select_final(None, None, None) == (None, True)


def test_sigterm_finalizes_standing_artifact_rc0():
    """Start bench.py, TERM it almost immediately, and require: exit 0,
    a FINAL last line (no interim flag), and the signal recorded in the
    error field — the driver's kill must never leave interim:true (or no
    line at all) as the round's standing record."""
    env = dict(os.environ)
    env["ORYX_BENCH_BUDGET_S"] = "120"
    env["ORYX_BENCH_POLL_S"] = "5"
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py")],
        cwd=str(REPO),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    time.sleep(2.0)
    proc.send_signal(signal.SIGTERM)
    try:
        out, _ = proc.communicate(timeout=90)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise AssertionError("bench.py did not exit after SIGTERM")
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out[-2000:]}"
    lines = [ln for ln in out.splitlines() if ln.strip().startswith("{")]
    assert lines, out[-2000:]
    final = json.loads(lines[-1])
    assert "interim" not in final
    assert "terminated by signal 15" in final.get("error", "")
    assert final["metric"].startswith("als_recommend")
