"""oryxlint: per-rule positive/negative fixtures + the tier-1 whole-tree
gate (zero unsuppressed findings on the current tree).

Each checker is proven in both directions: a small fixture snippet that
MUST produce the finding, and the adjacent compliant form that must not.
The whole-tree run is the ratchet — new code that blocks an event loop,
touches guarded state without its lock, side-effects inside a jitted
function, or drifts config/metric/ratchet vocabulary fails tier-1.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from tools.oryxlint.core import Project, run_lint  # noqa: E402
from tools.oryxlint.checkers.eventloop import EventLoopChecker  # noqa: E402
from tools.oryxlint.checkers.jaxpurity import JaxPurityChecker  # noqa: E402
from tools.oryxlint.checkers.lockdiscipline import LockDisciplineChecker  # noqa: E402


def _lint_fixture(tmp_path, source: str, checkers) -> tuple[list, list]:
    pkg = tmp_path / "oryx_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(textwrap.dedent(source), encoding="utf-8")
    return run_lint(tmp_path, checkers=checkers)


def _rules(findings) -> list[str]:
    return [f.rule for f in findings]


# -- event-loop blocking-call detector ---------------------------------------


def test_blocking_call_in_async_def_caught(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import time

        async def handler():
            time.sleep(1)
    """, [EventLoopChecker()])
    assert _rules(active) == ["blocking-call-on-loop"]
    assert "time.sleep" in active[0].message


def test_blocking_call_reached_transitively(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import subprocess

        def helper():
            subprocess.run(["true"])

        async def handler():
            helper()
    """, [EventLoopChecker()])
    assert _rules(active) == ["blocking-call-on-loop"]
    assert "handler -> helper" in active[0].message


def test_nonblocking_route_handler_is_a_root(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        def register(app):
            @app.route("GET", "/x", nonblocking=True)
            def handler(a, req):
                a.input_producer.send("k", "line")

            @app.route("POST", "/y")
            def worker_handler(a, req):
                a.input_producer.send("k", "line")  # worker pool: legal
    """, [EventLoopChecker()])
    assert len(active) == 1
    assert active[0].rule == "blocking-call-on-loop"
    assert "producer" in active[0].message


def test_offloop_annotation_honored(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import time

        def sampler():  # oryxlint: offloop (dedicated thread)
            time.sleep(2)

        async def handler():
            sampler()
    """, [EventLoopChecker()])
    assert active == []


# -- lock discipline ----------------------------------------------------------


_LOCK_FIXTURE = """
    import threading


    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)
            self.n = 0  # guarded-by: _lock
            self.view = None  # guarded-by: _lock (writes)

        def locked_write(self):
            with self._lock:
                self.n += 1

        def cond_alias_write(self):
            with self._cond:
                self.n += 1

        def lockfree_snapshot_read(self):
            return self.view

        def contract(self):  # oryxlint: holds=_lock
            return self.n
"""


def test_with_lock_and_alias_and_writes_qualifier_pass(tmp_path):
    active, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE, [LockDisciplineChecker()])
    assert active == []


def test_guarded_by_violation_caught(tmp_path):
    active, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE + """
        def racy(self):
            self.n += 1

    Shared.racy = racy
    """, [LockDisciplineChecker()])
    # note: module-level function attached post-hoc is outside the class —
    # the in-class violation form is what we assert on below
    active2, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE.replace(
        "def contract(self):  # oryxlint: holds=_lock",
        "def racy(self):\n            self.n += 1\n\n        def contract(self):  # oryxlint: holds=_lock",
    ), [LockDisciplineChecker()])
    assert _rules(active2) == ["guarded-by"]
    assert "self.n" in active2[0].message


def test_closure_does_not_inherit_held_lock(tmp_path):
    active, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE.replace(
        "def contract(self):  # oryxlint: holds=_lock",
        "def leak(self):\n"
        "            with self._lock:\n"
        "                return lambda: self.n\n\n"
        "        def contract(self):  # oryxlint: holds=_lock",
    ), [LockDisciplineChecker()])
    assert _rules(active) == ["guarded-by"]


def test_writes_qualifier_still_checks_stores(tmp_path):
    active, _ = _lint_fixture(tmp_path, _LOCK_FIXTURE.replace(
        "def contract(self):  # oryxlint: holds=_lock",
        "def unlocked_swap(self):\n            self.view = ()\n\n"
        "        def contract(self):  # oryxlint: holds=_lock",
    ), [LockDisciplineChecker()])
    assert _rules(active) == ["guarded-by"]
    assert "self.view" in active[0].message


# -- jax purity / donation ----------------------------------------------------


def test_jit_side_effect_caught(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import jax

        @jax.jit
        def impure(x):
            print("tracing")
            return x
    """, [JaxPurityChecker()])
    assert _rules(active) == ["jit-side-effect"]


def test_jit_closed_over_mutation_and_rng_caught(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        import numpy as np
        import jax

        hits = []

        @jax.jit
        def impure(x):
            hits.append(1)
            return x + np.random.rand()
    """, [JaxPurityChecker()])
    assert sorted(_rules(active)) == ["jit-side-effect", "jit-side-effect"]


def test_pure_jit_and_pallas_kernel_pass(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("k",))
        def pure(x, k):
            local = []
            local.append(k)  # local mutation is fine
            return jnp.sum(x) + len(local)

        def _kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2

        def build(pl):
            return pl.pallas_call(_kernel)
    """, [JaxPurityChecker()])
    assert active == []


def test_donation_reuse_caught_and_rebind_allowed(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        from functools import partial

        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def donated(buf, row):
            return buf + row

        def bug(a, b):
            out = donated(a, b)
            return out + a

        def carry_ok(a, b):
            a = donated(a, b)
            return a + b
    """, [JaxPurityChecker()])
    assert _rules(active) == ["donation-reuse"]
    assert "'a'" in active[0].message


def test_donates_annotation_conditional_wrapper(tmp_path):
    """`donates=0 when donate` (the scatter_rows contract): reuse after a
    donate=True call is flagged; the non-donating form is free."""
    active, _ = _lint_fixture(tmp_path, """
        def scatter(buf, rows, *, donate=False):  # oryxlint: donates=0 when donate
            return buf

        def serving_path_bug(view, rows):
            out = scatter(view, rows, donate=True)
            return out, view  # in-flight dispatches read a deleted buffer

        def double_buffer_ok(view, rows):
            out = scatter(view, rows)
            return out, view
    """, [JaxPurityChecker()])
    assert _rules(active) == ["donation-reuse"]
    assert "'view'" in active[0].message


def test_donated_wrapper_assignment_form_detected(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        from functools import partial

        import jax

        def _train(x, y, carry):
            return carry + x + y

        train_donated = partial(jax.jit, donate_argnums=(2,))(_train)

        def bug(x, y, c):
            out = train_donated(x, y, c)
            return out + c
    """, [JaxPurityChecker()])
    assert _rules(active) == ["donation-reuse"]


# -- suppression syntax -------------------------------------------------------


def test_suppression_comment_honored(tmp_path):
    active, suppressed = _lint_fixture(tmp_path, """
        import time

        async def handler():
            time.sleep(1)  # oryxlint: disable=blocking-call-on-loop
    """, [EventLoopChecker()])
    assert active == []
    assert _rules(suppressed) == ["blocking-call-on-loop"]


def test_unknown_rule_suppression_rejected(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        x = 1  # oryxlint: disable=no-such-rule
    """, [EventLoopChecker()])
    assert _rules(active) == ["unknown-rule"]
    assert "no-such-rule" in active[0].message


def test_unknown_rule_finding_is_not_suppressible(tmp_path):
    active, _ = _lint_fixture(tmp_path, """
        x = 1  # oryxlint: disable=unknown-rule,bogus-rule
    """, [EventLoopChecker()])
    assert "unknown-rule" in _rules(active)


# -- consistency rules through oryxlint ---------------------------------------


def test_config_rule_catches_undeclared_key(tmp_path):
    from tools.oryxlint.checkers import consistency

    ref_dir = tmp_path / "oryx_tpu" / "common"
    ref_dir.mkdir(parents=True)
    (ref_dir / "reference.conf").write_text(
        "oryx { id = \"x\" }\n", encoding="utf-8"
    )
    (tmp_path / "oryx_tpu" / "mod.py").write_text(
        'v = config.get_int("oryx.not.declared", 1)\n', encoding="utf-8"
    )
    findings = consistency.config_findings(tmp_path)
    assert ["config-keys"] == [f.rule for f in findings]
    assert "oryx.not.declared" in findings[0].message


def test_metric_rule_catches_undocumented_name(tmp_path):
    from tools.oryxlint.checkers import consistency

    (tmp_path / "oryx_tpu").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "oryx_tpu" / "mod.py").write_text(
        'NAME = "oryx_undocumented_total"\n', encoding="utf-8"
    )
    (tmp_path / "docs" / "observability.md").write_text(
        "| `oryx_ghost_metric` | gone |\nscore_mode\n", encoding="utf-8"
    )
    (tmp_path / "bench.py").write_text(
        '"qps_quantized" "approx_recall_at_10" "quantized_recall_at_10" '
        '"lsh_measured_recall_at_10"\n', encoding="utf-8"
    )
    findings = consistency.metric_findings(tmp_path)
    msgs = " | ".join(f.message for f in findings)
    assert "oryx_undocumented_total" in msgs  # code -> docs direction
    assert "oryx_ghost_metric" in msgs        # docs -> code reverse rule


# -- check_bench stale-pending ------------------------------------------------


def _bank(tmp_path, name: str, payload: dict) -> None:
    (tmp_path / name).write_text(json.dumps(payload), encoding="utf-8")


def test_stale_pending_fails_once_banked_artifact_measures_it(tmp_path):
    from tools import check_bench

    rows = [{
        "name": "qps_quantized", "platform": "tpu", "baseline": 1.0,
        "direction": "up", "pending": True, "pending_since": 8,
    }]
    # artifact OLDER than the declaration: flag is legitimate
    _bank(tmp_path, "BENCH_TPU_WINDOW_r05.json",
          {"final": {"platform": "tpu", "qps_quantized": 5.0}})
    assert check_bench.stale_pending_problems(rows, root=str(tmp_path)) == []
    # artifact from the declaring round or later measuring it: stale
    _bank(tmp_path, "BENCH_TPU_WINDOW_r09.json",
          {"final": {"platform": "tpu", "qps_quantized": 5.0}})
    problems = check_bench.stale_pending_problems(rows, root=str(tmp_path))
    assert len(problems) == 1 and "remove the pending flag" in problems[0]


def test_stale_pending_reads_parsed_shape_round_artifacts(tmp_path):
    """Driver round artifacts (BENCH_r{N}.json) nest their metrics under
    a `parsed` key — the scan must see them, or a CPU pending row could
    float forever."""
    from tools import check_bench

    rows = [{
        "name": "some_cpu_metric", "platform": "cpu", "baseline": 1.0,
        "direction": "up", "pending": True, "pending_since": 8,
    }]
    _bank(tmp_path, "BENCH_r09.json", {
        "n": 9, "rc": 0,
        "parsed": {"platform": "cpu", "some_cpu_metric": 2.5},
    })
    problems = check_bench.stale_pending_problems(rows, root=str(tmp_path))
    assert len(problems) == 1 and "round-9 cpu artifact" in problems[0]


def test_stale_pending_tolerates_malformed_rows(tmp_path):
    """A nameless pending row (already reported by the vocabulary check)
    or an unparseable pending_since must degrade, not traceback."""
    from tools import check_bench

    _bank(tmp_path, "BENCH_TPU_WINDOW_r09.json",
          {"final": {"platform": "tpu", "x": 1.0}})
    rows = [
        {"pending": True},  # nameless
        {"name": "x", "platform": "tpu", "baseline": 1.0, "direction": "up",
         "pending": True, "pending_since": "not-a-round"},
    ]
    problems = check_bench.stale_pending_problems(rows, root=str(tmp_path))
    # nameless row skipped; bad since falls back to the strict reading
    assert len(problems) == 1 and problems[0].startswith("x:")


def test_pending_survives_artifacts_that_do_not_measure_it(tmp_path):
    from tools import check_bench

    rows = [{
        "name": "qps_quantized", "platform": "tpu", "baseline": 1.0,
        "direction": "up", "pending": True, "pending_since": 8,
    }]
    # right platform, metric absent
    _bank(tmp_path, "BENCH_TPU_WINDOW_r09.json", {"final": {"platform": "tpu"}})
    # wrong platform, metric present
    _bank(tmp_path, "BENCH_r10.json",
          {"final": {"platform": "cpu", "qps_quantized": 5.0}})
    assert check_bench.stale_pending_problems(rows, root=str(tmp_path)) == []


def test_committed_ratchet_has_no_stale_pending_rows():
    from tools import check_bench

    metrics = check_bench.load_baseline(str(ROOT / "BASELINE_RATCHET.json"))
    assert check_bench.stale_pending_problems(metrics, root=str(ROOT)) == []
    for m in metrics:
        if m.get("pending"):
            assert "pending_since" in m, (
                f"{m['name']}: pending rows must record the declaring round"
            )


# -- CLI ----------------------------------------------------------------------


def test_cli_json_and_changed_modes():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.oryxlint", "--json"],
        cwd=ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == []
    assert "blocking-call-on-loop" in doc["rules"]

    proc = subprocess.run(
        [sys.executable, "-m", "tools.oryxlint", "--list-rules"],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    for rule in ("guarded-by", "jit-side-effect", "donation-reuse",
                 "config-keys", "metric-docs", "bench-ratchet"):
        assert rule in proc.stdout


# -- the tier-1 whole-tree gate ----------------------------------------------


def test_whole_tree_is_clean():
    """`python -m tools.oryxlint` on the tree: zero unsuppressed findings.

    This is the ratchet the new checkers hold: event-loop discipline,
    guarded-by lock discipline, jit purity/donation, and the
    config/metric/ratchet consistency contracts, all at once. Suppressed
    findings are allowed (each carries an in-source justification), but
    every suppression must name a real rule (unknown-rule is active)."""
    active, suppressed = run_lint(ROOT)
    rendered = "\n".join(f.render() for f in active)
    assert active == [], f"oryxlint findings on the tree:\n{rendered}"
    # the tree currently carries a known, justified suppression budget;
    # growing it should be a conscious review decision, not drift
    assert len(suppressed) <= 8, [f.render() for f in suppressed]


def test_production_annotations_are_load_bearing():
    """The annotation seeding is real, not decorative: the threaded core
    declares guarded attributes, holds-contracts, and offloop proofs the
    checkers actually consume."""
    project = Project.load(ROOT)
    by_path = {m.relpath: m for m in project.modules}
    guarded_files = [
        "oryx_tpu/common/metrics.py",
        "oryx_tpu/common/perfstats.py",
        "oryx_tpu/common/tracing.py",
        "oryx_tpu/serving/batcher.py",
        "oryx_tpu/fleet/front.py",
        "oryx_tpu/fleet/supervisor.py",
        "oryx_tpu/apps/als/serving.py",
    ]
    for rel in guarded_files:
        assert by_path[rel].guarded_lines, f"{rel}: no guarded-by seeds"
    assert by_path["oryx_tpu/serving/server.py"].offloop_lines, (
        "the lag-sampler offloop proof (PR 7 bug class) is gone"
    )
    assert by_path["oryx_tpu/apps/als/serving.py"].holds_lines, (
        "the 'call under _sync_lock' contracts lost their holds= form"
    )
