"""Typed hyperparameter ranges + grid/random search.

Mirrors framework/oryx-ml's param package (HyperParams.java:67-115,
GridSearch.java:30-70, RandomSearch.java:36-57): ranges come from config
values (scalar = fixed, list = categorical, {min,max} object = range),
grid search enumerates a capped cross-product with a per-parameter value
budget, random search samples combos through the ranges.
"""

from __future__ import annotations

import itertools
import math
from abc import ABC, abstractmethod
from typing import Any, Mapping, Sequence

import numpy as np

from oryx_tpu.common.rng import RandomManager

MAX_COMBOS = 65536


class HyperParamRange(ABC):
    @abstractmethod
    def trial_values(self, n: int) -> list:
        """Up to n representative values spanning the range (grid search)."""

    @abstractmethod
    def random_value(self, rng: np.random.Generator): ...


class Unordered(HyperParamRange):
    """Categorical set; also represents a fixed single value."""

    def __init__(self, values: Sequence):
        if not values:
            raise ValueError("empty value set")
        self.values = list(values)

    def trial_values(self, n: int) -> list:
        return self.values[: max(1, n)]

    def random_value(self, rng):
        return self.values[int(rng.integers(len(self.values)))]


class DiscreteRange(HyperParamRange):
    def __init__(self, lo: int, hi: int):
        if hi < lo:
            raise ValueError(f"bad range [{lo},{hi}]")
        self.lo, self.hi = int(lo), int(hi)

    def trial_values(self, n: int) -> list:
        if self.lo == self.hi or n <= 1:
            return [self.lo]
        span = self.hi - self.lo
        k = min(n, span + 1)
        return sorted({self.lo + round(i * span / (k - 1)) for i in range(k)})

    def random_value(self, rng):
        return int(rng.integers(self.lo, self.hi + 1))


class ContinuousRange(HyperParamRange):
    """Uniform, or log-uniform when the range spans multiple decades (the
    useful behavior for regularization-type params)."""

    def __init__(self, lo: float, hi: float):
        if hi < lo:
            raise ValueError(f"bad range [{lo},{hi}]")
        self.lo, self.hi = float(lo), float(hi)
        self.log = lo > 0 and hi / max(lo, 1e-30) >= 100.0

    def trial_values(self, n: int) -> list:
        if self.lo == self.hi or n <= 1:
            return [self.lo]
        if self.log:
            return list(np.geomspace(self.lo, self.hi, n))
        return list(np.linspace(self.lo, self.hi, n))

    def random_value(self, rng):
        if self.log:
            return float(np.exp(rng.uniform(math.log(self.lo), math.log(self.hi))))
        return float(rng.uniform(self.lo, self.hi))


class DiscreteAround(HyperParamRange):
    def __init__(self, value: int, step: int):
        self.value, self.step = int(value), int(step)

    def trial_values(self, n: int) -> list:
        if n <= 1 or self.step == 0:
            # step 0 pins the value; without this guard the growing-set
            # loop below never terminates for n >= 2
            return [self.value]
        out = {self.value}
        i = 1
        while len(out) < n:
            out |= {self.value - i * self.step, self.value + i * self.step}
            i += 1
        return sorted(out)[:n]

    def random_value(self, rng):
        return self.value + int(rng.integers(-1, 2)) * self.step


class ContinuousAround(HyperParamRange):
    def __init__(self, value: float, step: float):
        self.value, self.step = float(value), float(step)

    def trial_values(self, n: int) -> list:
        if n <= 1 or self.step == 0.0:
            return [self.value]  # step 0 pins the value (no [v, v] dups)
        half = (n - 1) // 2
        return [self.value + i * self.step for i in range(-half, n - half)]

    def random_value(self, rng):
        return float(self.value + rng.uniform(-1, 1) * self.step)


def from_config_value(v: Any) -> HyperParamRange:
    """Config value -> range: scalar = fixed, list = categorical,
    {min,max} = numeric range, {value,step} = around."""
    if isinstance(v, HyperParamRange):
        return v
    if isinstance(v, Mapping):
        if "min" in v and "max" in v:
            lo, hi = v["min"], v["max"]
            if isinstance(lo, int) and isinstance(hi, int):
                return DiscreteRange(lo, hi)
            return ContinuousRange(lo, hi)
        if "value" in v and "step" in v:
            val, step = v["value"], v["step"]
            if isinstance(val, int) and isinstance(step, int):
                return DiscreteAround(val, step)
            return ContinuousAround(val, step)
        raise ValueError(f"bad hyperparam object: {v!r}")
    if isinstance(v, (list, tuple)):
        return Unordered(v)
    return Unordered([v])


def grid_search(ranges: Mapping[str, HyperParamRange], how_many: int) -> list[dict]:
    """Full cross-product, with a per-param value budget chosen so the
    total stays near how_many (and hard-capped at MAX_COMBOS)."""
    names = list(ranges)
    if not names:
        return [{}]
    how_many = min(max(1, how_many), MAX_COMBOS)
    # spread the budget across parameters that can actually VARY: a fixed
    # scalar contributes exactly one value regardless, and counting it
    # would starve the real search axes (e.g. one varying lambda among
    # fixed features/alpha got a budget of 1 and the "grid" collapsed to
    # a single combo). set() dedupes degenerate ranges that return
    # repeated values.
    vary = sum(1 for n in names if len(set(ranges[n].trial_values(2))) > 1)
    per_param = max(1, int(round(how_many ** (1.0 / max(1, vary)))))
    value_lists = [ranges[n].trial_values(per_param) for n in names]
    combos = [dict(zip(names, vals)) for vals in itertools.product(*value_lists)]
    return combos[:MAX_COMBOS]


def random_search(ranges: Mapping[str, HyperParamRange], how_many: int) -> list[dict]:
    rng = RandomManager.get_random()
    names = list(ranges)
    if not names:
        return [{}]
    return [
        {n: ranges[n].random_value(rng) for n in names}
        for _ in range(max(1, how_many))
    ]


def choose_combos(
    ranges: Mapping[str, Any], candidates: int, strategy: str = "random"
) -> list[dict]:
    """Dispatch grid vs random like HyperParams.chooseHyperParameterCombos;
    1 candidate always means 'the default point' (first trial value)."""
    typed = {k: from_config_value(v) for k, v in ranges.items()}
    if candidates <= 1:
        return [{k: r.trial_values(1)[0] for k, r in typed.items()}]
    if strategy == "grid":
        return grid_search(typed, candidates)
    if strategy == "random":
        return random_search(typed, candidates)
    raise ValueError(f"unknown search strategy: {strategy!r}")
