"""In-process single-node Kafka protocol server for integration tests.

The analogue of the reference booting a real broker inside the test JVM
(framework/kafka-util src/test .../LocalKafkaBroker.java:44-60): the
kafka:// client in oryx_tpu/bus/kafka.py is exercised over real TCP sockets
speaking the real wire format (request framing, header v1, record batch v2
with baseOffset rewrite on append — what an actual broker does), so the
bus semantics (keyed partitioning, offset commit/fetch, earliest/latest
replay) are tested end-to-end without a JVM in the image.

Supports the API (key, version) pairs the client pins. Single node, no
replication, logs in memory.
"""

from __future__ import annotations

import socket
import struct
import threading

from oryx_tpu.bus.kafkawire import (
    API_API_VERSIONS,
    API_CREATE_TOPICS,
    API_DELETE_TOPICS,
    API_FETCH,
    API_FIND_COORDINATOR,
    API_LIST_OFFSETS,
    API_METADATA,
    API_OFFSET_COMMIT,
    API_OFFSET_FETCH,
    API_PRODUCE,
    ERR_NONE,
    ERR_TOPIC_ALREADY_EXISTS,
    ERR_UNKNOWN_TOPIC_OR_PARTITION,
    Reader,
    Writer,
)

_NODE_ID = 0

# record-batch v2 header layout constants (offsets within a batch blob)
_LAST_OFFSET_DELTA_AT = 23
_RECORD_COUNT_AT = 57


class _Partition:
    def __init__(self):
        # [(base_offset, last_offset, raw_batch_bytes)]
        self.batches: list[tuple[int, int, bytes]] = []
        self.end_offset = 0
        self.log_start = 0  # first retained offset (retention truncation)


class LocalKafkaTestBroker:
    """listen() -> serve on a free port until close().

    Fidelity knobs beyond the happy path (round-2 verdict: the protocol
    fake must be able to exercise what a real cluster throws at clients):

    - ``shared_from=other``: a second "node" sharing the first's log and
      group store — a 2-node cluster as far as coordinator movement is
      concerned.
    - ``move_coordinator(host, port)``: FindCoordinator now points there,
      and THIS node answers OffsetCommit/OffsetFetch with
      16 NOT_COORDINATOR until the client rediscovers.
    - ``inject_error(api_key, err, times)``: the next `times` requests of
      that API fail with `err` (per-partition where the API has them).
    - ``throttle_ms``: nonzero throttle_time_ms in produce/fetch
      responses (clients must parse and carry on).
    - ``append_raw_batch``: splice a foreign producer's record batch
      (e.g. gzip/snappy compressed) into the log verbatim.
    """

    def __init__(self, shared_from: "LocalKafkaTestBroker | None" = None):
        if shared_from is not None:
            self._topics = shared_from._topics
            self._group_offsets = shared_from._group_offsets
            self._lock = shared_from._lock
        else:
            self._topics: dict[str, list[_Partition]] = {}
            self._group_offsets: dict[tuple[str, str], dict[int, int]] = {}
            self._lock = threading.Lock()
        self._server: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._closed = False
        self.host = "127.0.0.1"
        self.port = 0
        self.throttle_ms = 0
        self._coordinator_addr: tuple[str, int] | None = None  # None = self
        self._injected: dict[int, list[int]] = {}  # api_key -> pending errs

    # -- fidelity knobs ----------------------------------------------------

    def move_coordinator(self, host: str, port: int) -> None:
        self._coordinator_addr = (host, port)

    def inject_error(self, api_key: int, err: int, times: int = 1) -> None:
        self._injected.setdefault(api_key, []).extend([err] * times)

    def _take_injected(self, api_key: int) -> int | None:
        errs = self._injected.get(api_key)
        if errs:
            return errs.pop(0)
        return None

    def append_raw_batch(self, topic: str, pidx: int, batch: bytes) -> int:
        """Append a foreign producer's wire batch verbatim (offsets
        rewritten like a real broker's log append). Returns base offset."""
        err, base = self._append(topic, pidx, batch)
        if err != ERR_NONE:
            raise RuntimeError(f"append failed: {err}")
        return base

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "LocalKafkaTestBroker":
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self.host, 0))
        self.port = self._server.getsockname()[1]
        self._server.listen(32)
        t = threading.Thread(target=self._accept_loop, daemon=True, name="kafka-test-accept")
        t.start()
        self._threads.append(t)
        return self

    @property
    def uri(self) -> str:
        return f"kafka://{self.host}:{self.port}"

    def close(self) -> None:
        self._closed = True
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- networking --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True, name="kafka-test-conn"
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed:
                hdr = self._recv_exact(conn, 4)
                if hdr is None:
                    return
                (n,) = struct.unpack(">i", hdr)
                payload = self._recv_exact(conn, n)
                if payload is None:
                    return
                r = Reader(payload)
                api_key = r.i16()
                api_version = r.i16()
                corr = r.i32()
                r.string()  # client id
                body = self._dispatch(api_key, api_version, r)
                out = Writer().i32(corr).raw(body).done()
                conn.sendall(Writer().i32(len(out)).raw(out).done())
        except (OSError, EOFError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, api_key: int, version: int, r: Reader) -> bytes:
        handlers = {
            API_METADATA: self._h_metadata,
            API_PRODUCE: self._h_produce,
            API_FETCH: self._h_fetch,
            API_LIST_OFFSETS: self._h_list_offsets,
            API_CREATE_TOPICS: self._h_create_topics,
            API_DELETE_TOPICS: self._h_delete_topics,
            API_FIND_COORDINATOR: self._h_find_coordinator,
            API_OFFSET_COMMIT: self._h_offset_commit,
            API_OFFSET_FETCH: self._h_offset_fetch,
            API_API_VERSIONS: self._h_api_versions,
        }
        h = handlers.get(api_key)
        if h is None:
            raise ValueError(f"unsupported api {api_key}")
        return h(version, r)

    # -- handlers (response bodies must match the versions the client pins) -

    def _h_api_versions(self, version: int, r: Reader) -> bytes:
        w = Writer().i16(ERR_NONE)
        apis = [(k, 0, 10) for k in (0, 1, 2, 3, 8, 9, 10, 18, 19, 20)]
        return w.array(apis, lambda w2, a: w2.i16(a[0]).i16(a[1]).i16(a[2])).done()

    def _h_metadata(self, version: int, r: Reader) -> bytes:
        wanted = r.array(Reader.string)
        with self._lock:
            names = list(self._topics) if wanted is None else [t for t in wanted]
            w = Writer()
            w.array(
                [(_NODE_ID, self.host, self.port, None)],
                lambda w2, b: w2.i32(b[0]).string(b[1]).i32(b[2]).string(b[3]),
            )
            w.i32(_NODE_ID)  # controller
            w.i32(len(names))
            for name in names:
                parts = self._topics.get(name)
                w.i16(ERR_NONE if parts else ERR_UNKNOWN_TOPIC_OR_PARTITION)
                w.string(name)
                w.i8(0)  # is_internal
                w.i32(len(parts) if parts else 0)
                for i in range(len(parts) if parts else 0):
                    w.i16(ERR_NONE).i32(i).i32(_NODE_ID)
                    w.array([_NODE_ID], Writer.i32)  # replicas
                    w.array([_NODE_ID], Writer.i32)  # isr
            return w.done()

    def _h_create_topics(self, version: int, r: Reader) -> bytes:
        n = r.i32()
        results = []
        with self._lock:
            for _ in range(n):
                name = r.string()
                partitions = r.i32()
                r.i16()  # replication factor
                na = r.i32()  # assignments
                for _ in range(max(0, na)):
                    r.i32()
                    r.array(Reader.i32)
                nc = r.i32()  # configs
                for _ in range(max(0, nc)):
                    r.string()
                    r.string()
                if name in self._topics:
                    results.append((name, ERR_TOPIC_ALREADY_EXISTS))
                else:
                    self._topics[name] = [_Partition() for _ in range(max(1, partitions))]
                    results.append((name, ERR_NONE))
        r.i32()  # timeout
        return Writer().array(results, lambda w, t: w.string(t[0]).i16(t[1])).done()

    def _h_delete_topics(self, version: int, r: Reader) -> bytes:
        names = r.array(Reader.string) or []
        r.i32()  # timeout
        results = []
        with self._lock:
            for name in names:
                if name in self._topics:
                    del self._topics[name]
                    results.append((name, ERR_NONE))
                else:
                    results.append((name, ERR_UNKNOWN_TOPIC_OR_PARTITION))
        return Writer().array(results, lambda w, t: w.string(t[0]).i16(t[1])).done()

    def _h_produce(self, version: int, r: Reader) -> bytes:
        r.string()  # transactional id
        r.i16()  # acks
        r.i32()  # timeout
        n_topics = r.i32()
        responses = []
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            part_resps = []
            for _ in range(n_parts):
                pidx = r.i32()
                batch = r.bytes_()
                inj = self._take_injected(API_PRODUCE)
                if inj is not None:
                    part_resps.append((pidx, inj, -1))
                    continue
                err, base = self._append(topic, pidx, batch)
                part_resps.append((pidx, err, base))
            responses.append((topic, part_resps))
        w = Writer()
        w.i32(len(responses))
        for topic, part_resps in responses:
            w.string(topic)
            w.array(
                part_resps,
                lambda w2, pr: w2.i32(pr[0]).i16(pr[1]).i64(pr[2]).i64(-1),
            )
        w.i32(self.throttle_ms)  # throttle
        return w.done()

    def _append(self, topic: str, pidx: int, batch: bytes | None) -> tuple[int, int]:
        with self._lock:
            parts = self._topics.get(topic)
            if parts is None or pidx >= len(parts):
                return ERR_UNKNOWN_TOPIC_OR_PARTITION, -1
            part = parts[pidx]
            if not batch:
                return ERR_NONE, part.end_offset
            # a real broker assigns offsets by rewriting baseOffset in the
            # batch header, then stores the blob verbatim
            (last_delta,) = struct.unpack_from(">i", batch, _LAST_OFFSET_DELTA_AT)
            base = part.end_offset
            rewritten = struct.pack(">q", base) + batch[8:]
            part.batches.append((base, base + last_delta, rewritten))
            part.end_offset = base + last_delta + 1
            return ERR_NONE, base

    def _h_fetch(self, version: int, r: Reader) -> bytes:
        r.i32()  # replica
        r.i32()  # max wait
        r.i32()  # min bytes
        r.i32()  # max bytes
        r.i8()  # isolation
        n_topics = r.i32()
        out_topics = []
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            parts_out = []
            for _ in range(n_parts):
                pidx = r.i32()
                fetch_offset = r.i64()
                r.i32()  # partition max bytes
                inj = self._take_injected(API_FETCH)
                if inj is not None:
                    parts_out.append((pidx, inj, -1, b""))
                    continue
                parts_out.append((pidx, *self._fetch(topic, pidx, fetch_offset)))
            out_topics.append((topic, parts_out))
        w = Writer().i32(self.throttle_ms)  # throttle
        w.i32(len(out_topics))
        for topic, parts_out in out_topics:
            w.string(topic)
            w.i32(len(parts_out))
            for pidx, err, hw, blob in parts_out:
                w.i32(pidx).i16(err).i64(hw).i64(hw)
                w.i32(0)  # aborted txns (empty array)
                w.bytes_(blob if blob else None)
        return w.done()

    def _fetch(self, topic: str, pidx: int, offset: int) -> tuple[int, int, bytes]:
        with self._lock:
            parts = self._topics.get(topic)
            if parts is None or pidx >= len(parts):
                return ERR_UNKNOWN_TOPIC_OR_PARTITION, -1, b""
            part = parts[pidx]
            if offset < part.log_start:
                return 1, part.end_offset, b""  # OFFSET_OUT_OF_RANGE
            blobs = [
                raw
                for base, last, raw in part.batches
                if last >= offset
            ]
            return ERR_NONE, part.end_offset, b"".join(blobs)

    def truncate(self, topic: str, pidx: int, new_start: int) -> None:
        """Simulate retention: drop batches wholly below new_start."""
        with self._lock:
            part = self._topics[topic][pidx]
            part.log_start = max(part.log_start, new_start)
            part.batches = [
                (b, l, raw) for b, l, raw in part.batches if l >= part.log_start
            ]

    def _h_list_offsets(self, version: int, r: Reader) -> bytes:
        r.i32()  # replica
        n_topics = r.i32()
        out = []
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            parts = []
            for _ in range(n_parts):
                pidx = r.i32()
                ts = r.i64()
                with self._lock:
                    plist = self._topics.get(topic)
                    if plist is None or pidx >= len(plist):
                        parts.append((pidx, ERR_UNKNOWN_TOPIC_OR_PARTITION, -1))
                    else:
                        off = plist[pidx].log_start if ts == -2 else plist[pidx].end_offset
                        parts.append((pidx, ERR_NONE, off))
            out.append((topic, parts))
        w = Writer()
        w.i32(len(out))
        for topic, parts in out:
            w.string(topic)
            w.array(
                parts, lambda w2, p: w2.i32(p[0]).i16(p[1]).i64(-1).i64(p[2])
            )
        return w.done()

    def _h_find_coordinator(self, version: int, r: Reader) -> bytes:
        r.string()  # group
        inj = self._take_injected(API_FIND_COORDINATOR)
        if inj is not None:
            return Writer().i16(inj).i32(-1).string(None).i32(-1).done()
        host, port = self._coordinator_addr or (self.host, self.port)
        return Writer().i16(ERR_NONE).i32(_NODE_ID).string(host).i32(port).done()

    def _h_offset_commit(self, version: int, r: Reader) -> bytes:
        group = r.string()
        r.i32()  # generation
        r.string()  # member
        r.i64()  # retention
        n_topics = r.i32()
        # a demoted node refuses commits until the client rediscovers
        refuse = self._take_injected(API_OFFSET_COMMIT)
        if refuse is None and self._coordinator_addr is not None:
            refuse = 16  # NOT_COORDINATOR
        out = []
        with self._lock:
            for _ in range(n_topics):
                topic = r.string()
                n_parts = r.i32()
                parts = []
                store = self._group_offsets.setdefault((group, topic), {})
                for _ in range(n_parts):
                    pidx = r.i32()
                    off = r.i64()
                    r.string()  # metadata
                    if refuse is not None:
                        parts.append((pidx, refuse))
                        continue
                    store[pidx] = off
                    parts.append((pidx, ERR_NONE))
                out.append((topic, parts))
        w = Writer()
        w.i32(len(out))
        for topic, parts in out:
            w.string(topic)
            w.array(parts, lambda w2, p: w2.i32(p[0]).i16(p[1]))
        return w.done()

    def _h_offset_fetch(self, version: int, r: Reader) -> bytes:
        group = r.string()
        n_topics = r.i32()
        refuse = self._take_injected(API_OFFSET_FETCH)
        if refuse is None and self._coordinator_addr is not None:
            refuse = 16  # NOT_COORDINATOR
        out = []
        with self._lock:
            for _ in range(n_topics):
                topic = r.string()
                pidxs = r.array(Reader.i32) or []
                store = self._group_offsets.get((group, topic), {})
                out.append(
                    (topic, [(p, store.get(p, -1)) for p in pidxs])
                )
        w = Writer()
        w.i32(len(out))
        for topic, parts in out:
            w.string(topic)
            w.array(
                parts,
                lambda w2, p: w2.i32(p[0])
                .i64(-1 if refuse is not None else p[1])
                .string(None)
                .i16(refuse if refuse is not None else ERR_NONE),
            )
        return w.done()
