"""Filesystem + network helpers.

Mirrors the reference's IOUtils (framework/oryx-common .../io/IOUtils.java):
free-port selection for test servers, recursive delete, atomic renames, and
directory listing ordered by the generation-timestamp naming convention.
"""

from __future__ import annotations

import os
import re
import shutil
import socket
import time
from pathlib import Path


def choose_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def delete_recursively(path: str | Path) -> None:
    p = Path(path)
    if p.is_dir():
        shutil.rmtree(p, ignore_errors=True)
    elif p.exists():
        p.unlink(missing_ok=True)


def mkdirs(path: str | Path) -> Path:
    p = Path(path)
    p.mkdir(parents=True, exist_ok=True)
    return p


def atomic_rename(src: str | Path, dst: str | Path) -> None:
    """Atomic move used to publish the winning model candidate
    (reference MLUpdate.java:199-205 fs.rename)."""
    os.replace(str(src), str(dst))


_TS_DIR_RE = re.compile(r"^oryx-(\d+)$|^(\d{10,})$")


def timestamp_from_dirname(name: str) -> int | None:
    """Extract the epoch-millis timestamp from a generation dir name
    (oryx-<ts> data dirs, bare <ts> model dirs), the convention of
    SaveToHDFSFunction/DeleteOldDataFn."""
    m = _TS_DIR_RE.match(name)
    if not m:
        return None
    return int(m.group(1) or m.group(2))


def list_generation_dirs(root: str | Path) -> list[Path]:
    r = Path(strip_scheme(str(root)))
    if not r.is_dir():
        return []
    out = [p for p in r.iterdir() if p.is_dir() and timestamp_from_dirname(p.name) is not None]
    return sorted(out, key=lambda p: timestamp_from_dirname(p.name) or 0)


def delete_older_than(root: str | Path, max_age_hours: int, now_ms: int | None = None) -> int:
    """TTL enforcement over timestamped dirs (reference DeleteOldDataFn.java)."""
    if max_age_hours < 0:
        return 0
    now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
    cutoff = now_ms - max_age_hours * 3600 * 1000
    n = 0
    for p in list_generation_dirs(root):
        ts = timestamp_from_dirname(p.name)
        if ts is not None and ts < cutoff:
            delete_recursively(p)
            n += 1
    return n


def strip_scheme(uri: str) -> str:
    """file:/x, file:///x → /x ; other schemes unchanged-but-stripped."""
    if uri.startswith("file://"):
        return uri[len("file://") :] or "/"
    if uri.startswith("file:"):
        return uri[len("file:") :]
    return uri
