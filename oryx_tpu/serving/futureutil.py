"""Race-tolerant Future resolution.

Several producers may race to resolve the same concurrent.futures.Future:
the batcher's dispatcher vs the watchdog's host-side drain, a deferred
handler's completion vs the async frontend cancelling on client
disconnect. Losing such a race raises InvalidStateError from
set_result/set_exception — which, inside a done-callback or a dispatcher
loop, turns one already-resolved request into spurious failures for its
neighbours. Every resolution site goes through these helpers instead.
"""

from __future__ import annotations

from concurrent.futures import Future, InvalidStateError


def try_set_result(future: Future, result) -> bool:
    """Resolve `future` with `result` unless another producer (or a
    cancellation) got there first. Returns True iff this call delivered."""
    if future.done():
        return False
    try:
        future.set_result(result)
        return True
    except InvalidStateError:
        return False


def try_set_exception(future: Future, exc: BaseException) -> bool:
    """Fail `future` with `exc` unless already resolved/cancelled.
    Returns True iff this call delivered the exception."""
    if future.done():
        return False
    try:
        future.set_exception(exc)
        return True
    except InvalidStateError:
        return False
