"""Project-consistency checkers (rules ``config-keys``, ``metric-docs``,
``bench-ratchet``, ``flight-events``).

These absorb the one-off tools this repo grew over PRs 4-8 into the
checker SPI — the old entry points (tools/check_config.py,
tools/check_metrics.py) remain as thin CLI wrappers:

- ``config-keys``: every ``oryx.*`` key read through a Config accessor
  is declared in common/reference.conf, and every key declared under a
  strict robustness block (faults/retry/quarantine/shed) is read
  somewhere — a dead recovery knob misleads operators.
- ``metric-docs``: every ``oryx_*`` metric name in code matches the
  naming contract and has a row in docs/observability.md, and every
  documented row still exists in code (the reverse docs rule) — plus the
  score-mode bench/doc vocabulary.
- ``bench-ratchet``: every metric locked in BASELINE_RATCHET.json still
  exists in bench.py's output vocabulary, and no ``pending`` row has
  outlived a banked artifact of its platform that measures it
  (tools/check_bench.py owns that artifact scan).
- ``flight-events``: every flight-recorder ``record(kind="...")`` call
  site uses a kind registered in the ``EVENT_KINDS`` catalog
  (oryx_tpu/common/flightrec.py), and every cataloged kind has a row in
  docs/observability.md's flight-recorder event catalog (both
  directions) — the config-key/metric-docs pattern applied to the black
  box, so the event schema cannot drift silently.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tools.oryxlint.core import Checker, Finding, Project

# A Config accessor taking a literal oryx.* key as its first argument.
# \s* spans newlines, so wrapped call sites resolve too. Keys containing
# "{" are f-string compositions and excluded by the character class.
ACCESSOR = re.compile(
    r"\.(?:get|get_string|get_int|get_float|get_bool|get_list|get_config|has)"
    r"\(\s*[bru]?[\"'](oryx\.[A-Za-z0-9_.\-]+)[\"']"
)

# Blocks whose declared keys must each be READ by code (reverse check).
STRICT_BLOCKS = (
    "oryx.monitoring.faults",
    "oryx.monitoring.retry",
    "oryx.monitoring.quarantine",
    "oryx.serving.api.shed",
)

VALID_METRIC_NAME = re.compile(r"^oryx_[a-z0-9_]+$")
# A whole string literal that is an oryx_-prefixed identifier. Literals
# with any other characters (spaces, braces, dots) are scrape patterns or
# prose, not metric registrations, and are skipped on purpose.
METRIC_LITERAL = re.compile(r"""["'](oryx_[A-Za-z0-9_]+)["']""")
# A reference-table row whose first cell is the backticked metric name.
DOC_ROW = re.compile(r"^\|\s*`(oryx_[^`]+)`", re.M)

# Not metrics: the package's own name appears as a string in a few places.
METRIC_IGNORE = {"oryx_tpu"}

# Score-mode vocabulary (PR 8): bench fields the serving-mode claims ride
# on, and the label key the batcher's dispatch records carry. PR 11 adds
# the shard-scaling vocabulary (sharded top-k + measured train MFU) and
# the per-shard sync label.
REQUIRED_BENCH_FIELDS = (
    "qps_quantized",
    "approx_recall_at_10",
    "quantized_recall_at_10",
    "lsh_measured_recall_at_10",
    # the live shadow-rescore sampler's runtime recall (ISSUE 15): bench
    # http, tools/quality_nightly.py, and oryx_live_recall_at_k share
    # this one vocabulary
    "live_recall_at_10",
    "shard_topk_scaling_2shard",
    "train_mfu",
)
REQUIRED_DOC_TOKENS = ("score_mode", "shard", "signal", "phase", "cause")

# Hot-path latency-attribution vocabulary (ISSUE 17): the perfattr
# families (common/perfattr.py) must stay BOTH registered in code and
# documented — dashboards, `oryx perf`, and the latency-budget runbook
# all key on these exact names, so a rename must fail tier-1 loudly
# rather than silently orphan them.
REQUIRED_PERFATTR_FAMILIES = (
    "oryx_request_phase_seconds",
    "oryx_device_idle_gap_seconds",
    "oryx_xla_compile_seconds",
    "oryx_xla_compiles_total",
)


# -- collectors (shared with the thin CLI wrappers) --------------------------


def _package_texts(
    package: Path, root: Path, texts: dict[str, str] | None
) -> list[tuple[str, str]]:
    """(relpath, source) pairs under oryx_tpu/, from an already-loaded
    text cache (the lint run's Project) or from disk (the CLI wrappers)."""
    prefix = str(package.relative_to(root))
    if texts is not None:
        return sorted(
            (rel, t) for rel, t in texts.items()
            if rel.startswith(prefix + "/") or rel.startswith(prefix + "\\")
        )
    return [
        (str(py.relative_to(root)), py.read_text(encoding="utf-8"))
        for py in sorted(package.rglob("*.py"))
    ]


def code_config_keys(
    package: Path, root: Path, texts: dict[str, str] | None = None
) -> dict[str, tuple[str, int]]:
    """key -> (relpath, line) of the first literal oryx.* accessor read."""
    keys: dict[str, tuple[str, int]] = {}
    for rel, text in _package_texts(package, root, texts):
        for m in ACCESSOR.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            keys.setdefault(m.group(1), (rel, line))
    return keys


def code_metric_names(
    package: Path, root: Path, texts: dict[str, str] | None = None
) -> dict[str, tuple[str, int]]:
    """name -> (relpath, line) of the first metric-shaped literal."""
    names: dict[str, tuple[str, int]] = {}
    for rel, text in _package_texts(package, root, texts):
        for m in METRIC_LITERAL.finditer(text):
            name = m.group(1)
            if name not in METRIC_IGNORE:
                line = text.count("\n", 0, m.start()) + 1
                names.setdefault(name, (rel, line))
    return names


def doc_metric_names(doc: Path) -> set[str]:
    return set(DOC_ROW.findall(doc.read_text(encoding="utf-8")))


def reference_config(reference: Path):
    from oryx_tpu.common.config import parse_config

    return parse_config(reference.read_text(encoding="utf-8"))


# -- problem builders ---------------------------------------------------------


def config_problems(code: dict[str, str], ref) -> list[str]:
    """Key-level drift messages from a key->where map and a parsed
    reference config — the shared core the thin CLI wrapper
    (tools/check_config.py) and the rule both render from."""
    problems: list[str] = []
    for key in sorted(code):
        if not ref.has(key):
            problems.append(
                f"{key} ({code[key]}): read in code but not declared in "
                "common/reference.conf"
            )
    flat = ref.flatten()
    for block in STRICT_BLOCKS:
        for key in sorted(k for k in flat if k.startswith(block + ".")):
            if key not in code:
                problems.append(
                    f"{key}: declared in common/reference.conf but never "
                    "read by any Config accessor — a dead robustness knob "
                    "misleads operators about what recovery is configured"
                )
    return problems


def metric_doc_problems(
    code: dict[str, str], doc_names: set[str]
) -> list[str]:
    """Name-level drift messages from a name->where map and the doc-table
    names — shared by tools/check_metrics.py and the rule."""
    problems: list[str] = []
    for name in sorted(code):
        where = code[name]
        if not VALID_METRIC_NAME.match(name):
            problems.append(
                f"{name} ({where}): does not match ^oryx_[a-z0-9_]+$"
            )
        elif name not in doc_names:
            problems.append(
                f"{name} ({where}): missing from the docs/observability.md "
                "metric reference table"
            )
    for name in sorted(doc_names - set(code)):
        problems.append(
            f"{name}: documented in docs/observability.md but not found "
            "anywhere under oryx_tpu/"
        )
    problems.extend(perfattr_family_problems(set(code), doc_names))
    return problems


def perfattr_family_problems(
    code_names: set[str], doc_names: set[str]
) -> list[str]:
    """The latency-attribution families must exist on both sides — the
    generic drift checks only see names that exist SOMEWHERE, so a family
    deleted from both code and docs would otherwise pass silently."""
    problems: list[str] = []
    for name in REQUIRED_PERFATTR_FAMILIES:
        if name not in code_names:
            problems.append(
                f"{name}: required latency-attribution family not "
                "registered anywhere under oryx_tpu/ (common/perfattr.py)"
            )
        if name not in doc_names:
            problems.append(
                f"{name}: required latency-attribution family missing "
                "from the docs/observability.md metric reference table"
            )
    return problems


def config_findings(
    root: Path, texts: dict[str, str] | None = None
) -> list[Finding]:
    package = root / "oryx_tpu"
    reference = package / "common" / "reference.conf"
    ref_rel = str(reference.relative_to(root))
    if not reference.exists():
        return [Finding(ref_rel, 1, "config-keys", "missing reference.conf")]
    ref = reference_config(reference)
    code = code_config_keys(package, root, texts)
    out: list[Finding] = []
    for key in sorted(code):
        where, line = code[key]
        if not ref.has(key):
            out.append(Finding(
                where, line, "config-keys",
                f"{key} read in code but not declared in {ref_rel}",
            ))
    flat = ref.flatten()
    for block in STRICT_BLOCKS:
        for key in sorted(k for k in flat if k.startswith(block + ".")):
            if key not in code:
                out.append(Finding(
                    ref_rel, 1, "config-keys",
                    f"{key} declared in {ref_rel} but never read by any "
                    "Config accessor — a dead robustness knob misleads "
                    "operators about what recovery is configured",
                ))
    return out


def metric_findings(
    root: Path, texts: dict[str, str] | None = None
) -> list[Finding]:
    package = root / "oryx_tpu"
    doc = root / "docs" / "observability.md"
    doc_rel = str(doc.relative_to(root))
    if not doc.exists():
        return [Finding(doc_rel, 1, "metric-docs", "missing observability.md")]
    code = code_metric_names(package, root, texts)
    doc_names = doc_metric_names(doc)
    out: list[Finding] = []
    for name in sorted(code):
        where, line = code[name]
        if not VALID_METRIC_NAME.match(name):
            out.append(Finding(
                where, line, "metric-docs",
                f"{name} does not match ^oryx_[a-z0-9_]+$",
            ))
        elif name not in doc_names:
            out.append(Finding(
                where, line, "metric-docs",
                f"{name} missing from the {doc_rel} metric reference table",
            ))
    for name in sorted(doc_names - set(code)):
        out.append(Finding(
            doc_rel, 1, "metric-docs",
            f"{name} documented in {doc_rel} but not found anywhere under "
            "oryx_tpu/",
        ))
    for problem in perfattr_family_problems(set(code), doc_names):
        out.append(Finding(doc_rel, 1, "metric-docs", problem))
    bench = root / "bench.py"
    bench_text = bench.read_text(encoding="utf-8") if bench.exists() else ""
    for name in REQUIRED_BENCH_FIELDS:
        if not re.search(rf'"{re.escape(name)}"', bench_text):
            out.append(Finding(
                "bench.py", 1, "metric-docs",
                f"{name}: required bench vocabulary missing from bench.py",
            ))
    doc_text = doc.read_text(encoding="utf-8")
    for tok in REQUIRED_DOC_TOKENS:
        if tok not in doc_text:
            out.append(Finding(
                doc_rel, 1, "metric-docs",
                f"{tok}: required label name missing from {doc_rel}",
            ))
    return out


# Heading of the docs table the flight-event catalog must mirror; rows
# under it are parsed until the next heading.
FLIGHT_DOC_HEADING = "### Flight-recorder event catalog"
FLIGHT_DOC_ROW = re.compile(r"^\|\s*`([a-z0-9\-]+)`\s*\|")


def flight_doc_kinds(doc: Path) -> set[str]:
    """Event kinds documented in the flight-recorder catalog table (the
    section between its heading and the next heading)."""
    kinds: set[str] = set()
    in_section = False
    for line in doc.read_text(encoding="utf-8").splitlines():
        if line.strip().startswith("#"):
            in_section = line.strip() == FLIGHT_DOC_HEADING
            continue
        if in_section:
            m = FLIGHT_DOC_ROW.match(line)
            if m:
                kinds.add(m.group(1))
    return kinds


def _flight_call_kinds(tree: ast.AST) -> list[tuple[int, str]]:
    """(line, kind) for every ``<recv>.record(kind="literal", ...)`` call
    in a module. The ``kind=`` keyword with a string-literal value is the
    flight recorder's signature shape (the method makes it keyword-only);
    non-literal kinds are skipped — confident-only, like the dataflow
    checkers."""
    out: list[tuple[int, str]] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "record"
        ):
            continue
        for kw in node.keywords:
            if (
                kw.arg == "kind"
                and isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, str)
            ):
                out.append((node.lineno, kw.value.value))
    return out


def flight_findings(root: Path, project: Project | None = None) -> list[Finding]:
    from oryx_tpu.common.flightrec import EVENT_KINDS

    doc = root / "docs" / "observability.md"
    doc_rel = str(doc.relative_to(root))
    if not doc.exists():
        return [Finding(doc_rel, 1, "flight-events", "missing observability.md")]
    out: list[Finding] = []
    modules = project.modules if project is not None else []
    for mod in modules:
        for line, kind in _flight_call_kinds(mod.tree):
            if kind not in EVENT_KINDS:
                out.append(Finding(
                    mod.relpath, line, "flight-events",
                    f"{kind!r} is not a registered flight-event kind — add "
                    "it to EVENT_KINDS (oryx_tpu/common/flightrec.py) and "
                    f"the {doc_rel} event catalog, or fix the typo",
                ))
    doc_kinds = flight_doc_kinds(doc)
    for kind in sorted(set(EVENT_KINDS) - doc_kinds):
        out.append(Finding(
            doc_rel, 1, "flight-events",
            f"{kind}: registered in EVENT_KINDS but missing from the "
            f"{doc_rel} flight-recorder event catalog",
        ))
    for kind in sorted(doc_kinds - set(EVENT_KINDS)):
        out.append(Finding(
            doc_rel, 1, "flight-events",
            f"{kind}: documented in the {doc_rel} flight-recorder event "
            "catalog but not registered in EVENT_KINDS",
        ))
    return out


def ratchet_findings(root: Path) -> list[Finding]:
    import json

    ratchet = root / "BASELINE_RATCHET.json"
    bench = root / "bench.py"
    out: list[Finding] = []
    if not ratchet.exists():
        return [Finding("BASELINE_RATCHET.json", 1, "bench-ratchet", "missing")]
    try:
        metrics = json.loads(ratchet.read_text(encoding="utf-8"))["metrics"]
    except (json.JSONDecodeError, KeyError, TypeError) as e:
        return [Finding(
            "BASELINE_RATCHET.json", 1, "bench-ratchet", f"unparseable ({e})"
        )]
    bench_text = bench.read_text(encoding="utf-8") if bench.exists() else ""
    for m in metrics:
        name = m.get("name")
        if not name:
            out.append(Finding(
                "BASELINE_RATCHET.json", 1, "bench-ratchet",
                f"metric entry without a name: {m}",
            ))
        elif not re.search(rf'"{re.escape(name)}"', bench_text):
            out.append(Finding(
                "BASELINE_RATCHET.json", 1, "bench-ratchet",
                f"{name}: ratcheted but bench.py never emits a field of "
                "that name — the ratchet would fail every run as 'missing'",
            ))
    # every pending row must record its declaring round, or the stale
    # check below could never age it out
    for m in metrics:
        if m.get("pending") and not m.get("pending_since"):
            out.append(Finding(
                "BASELINE_RATCHET.json", 1, "bench-ratchet",
                f"{m.get('name')}: pending row without pending_since — "
                "record the declaring bench round so the flag can be "
                "aged out once an artifact measures it",
            ))
    # stale `pending` rows: a banked artifact of the right platform now
    # measures the metric, so the flag should have been removed by the PR
    # that banked it (tools/check_bench.py owns the artifact scan)
    from tools import check_bench

    for problem in check_bench.stale_pending_problems(metrics, root=str(root)):
        out.append(Finding("BASELINE_RATCHET.json", 1, "bench-ratchet", problem))
    return out


class ConsistencyChecker(Checker):
    name = "consistency"
    rules = {
        "config-keys": (
            "oryx.* config keys read in code must be declared in "
            "reference.conf; robustness-block keys must be read somewhere"
        ),
        "metric-docs": (
            "oryx_* metric names must match the naming contract and stay "
            "in lockstep with docs/observability.md (both directions)"
        ),
        "bench-ratchet": (
            "BASELINE_RATCHET.json rows must exist in bench.py's output "
            "vocabulary, and pending rows must not outlive a banked "
            "artifact that measures them"
        ),
        "flight-events": (
            "flight-recorder record(kind=...) call sites must use a kind "
            "registered in EVENT_KINDS, and the docs event catalog must "
            "match the registry in both directions"
        ),
    }
    severities = {
        "metric-docs": "warning",
        "bench-ratchet": "warning",
        "flight-events": "warning",
    }
    fix_hints = {
        "config-keys": (
            "declare the key in common/reference.conf (or read/remove the "
            "dead robustness knob)"
        ),
        "metric-docs": (
            "add/remove the row in docs/observability.md so code and docs "
            "agree in both directions"
        ),
        "bench-ratchet": (
            "update BASELINE_RATCHET.json: fix the metric name, add "
            "pending_since, or lock the measured baseline and drop the "
            "pending flag"
        ),
        "flight-events": (
            "register the kind in EVENT_KINDS "
            "(oryx_tpu/common/flightrec.py) and add/remove its row in the "
            "docs/observability.md flight-recorder event catalog"
        ),
    }

    def check(self, project: Project) -> list[Finding]:
        root = project.root
        # reuse the lint run's already-loaded sources instead of a second
        # and third full-tree read
        texts = {m.relpath: m.text for m in project.modules}
        out: list[Finding] = []
        out.extend(config_findings(root, texts))
        out.extend(metric_findings(root, texts))
        out.extend(ratchet_findings(root))
        out.extend(flight_findings(root, project))
        return out
