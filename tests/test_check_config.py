"""tools/check_config.py wired as a tier-1 gate: every oryx.* key the
code reads must be declared in common/reference.conf — new knobs (e.g.
the oryx.batch.train.* family) cannot silently drift out of the packaged
defaults."""

from __future__ import annotations

import importlib.util
import pathlib


def _load_tool():
    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "check_config", root / "tools" / "check_config.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_config_key_declared(capsys):
    tool = _load_tool()
    rc = tool.main()
    out = capsys.readouterr()
    assert rc == 0, f"config/reference.conf drift:\n{out.err}"


def test_checker_catches_undeclared_key(monkeypatch):
    """The checker must actually fail on a key missing from the defaults."""
    tool = _load_tool()
    real = tool.code_config_keys

    def with_extra():
        keys = real()
        keys["oryx.totally.new-knob"] = "somewhere.py"
        return keys

    monkeypatch.setattr(tool, "code_config_keys", with_extra)
    assert tool.main() == 1


def test_checker_resolves_wrapped_and_fstring_calls(tmp_path, monkeypatch):
    """Wrapped call sites resolve; f-string compositions are skipped."""
    tool = _load_tool()
    src = (
        'x = config.get_int(\n    "oryx.batch.streaming.generation-interval-sec"\n)\n'
        'y = config.get(f"oryx.als.{name}", None)\n'
    )
    found = tool.ACCESSOR.findall(src)
    assert found == ["oryx.batch.streaming.generation-interval-sec"]


def test_checker_catches_dead_robustness_knob(monkeypatch):
    """The reverse check: a key declared under a strict robustness block
    (faults/retry/quarantine/shed) that nothing reads must fail — a dead
    knob misleads operators about what recovery is configured."""
    tool = _load_tool()
    real = tool.code_config_keys

    def without_one():
        keys = real()
        keys.pop("oryx.monitoring.retry.attempts")
        return keys

    monkeypatch.setattr(tool, "code_config_keys", without_one)
    assert tool.main() == 1


def test_robustness_keys_present():
    """Spot-check the failure-containment knobs are both read in code and
    declared — the coverage this PR's satellite extends the checker to."""
    tool = _load_tool()
    code = tool.code_config_keys()
    ref = tool.reference_config()
    for key in (
        "oryx.monitoring.faults.enabled",
        "oryx.monitoring.faults.plan",
        "oryx.monitoring.faults.seed",
        "oryx.monitoring.retry.attempts",
        "oryx.monitoring.retry.base-ms",
        "oryx.monitoring.retry.deadline-ms",
        "oryx.monitoring.quarantine.dir",
        "oryx.monitoring.quarantine.max-attempts",
        "oryx.serving.api.shed.max-queue",
        "oryx.serving.api.shed.retry-after-sec",
        "oryx.serving.api.max-staleness-sec",
    ):
        assert key in code, f"{key} no longer read anywhere"
        assert ref.has(key), f"{key} missing from reference.conf"


def test_known_keys_present():
    """Spot-check the new incremental/warm-start keys are both read in
    code and declared — the exact drift this satellite exists to stop."""
    tool = _load_tool()
    code = tool.code_config_keys()
    ref = tool.reference_config()
    for key in (
        "oryx.batch.train.warm-start",
        "oryx.batch.train.tol",
        "oryx.batch.train.min-iterations",
        "oryx.batch.storage.incremental.enabled",
        "oryx.batch.storage.incremental.max-drift-fraction",
    ):
        assert key in code, f"{key} no longer read anywhere"
        assert ref.has(key), f"{key} missing from reference.conf"
