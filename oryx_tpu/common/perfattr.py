"""Hot-path latency attribution: request phase budgets, device
idle-gap classification, XLA compile telemetry, and burn-triggered
profile capture.

PR 13/14 built the observability plane that says *that* serving latency
is bad (SLO burn rates, traces, flight ring); this module is the half
that says *where* the time goes, so ROADMAP item 2 ("push the hot path
until the device is the bottleneck") has an instrument to aim with:

- **Request phase budgets.** Every request carries a ``PhaseLedger`` —
  a cheap append-only list of ``(phase, start, seconds)`` stamps the
  frontends and the batcher fill in as the request traverses parse →
  auth → queue_wait → batch_wait → pad → device (or host_fallback) →
  serialize → write. The frontend flushes the ledger once after the
  response bytes are written: each stamp lands in the
  ``oryx_request_phase_seconds{phase}`` histogram (with metric→trace
  exemplars) and — when tracing is on — as a ``phase.<name>`` child
  span under the request's root span, so /fleet/traces renders a
  waterfall instead of one opaque span. A rolling window of stamps
  backs ``budget()``: per-phase p50/p99 and share-of-total, the
  "latency budget" /healthz advertises and the fleet front federates
  into /fleet/status.

- **Device idle-gap attribution.** The batcher's dispatcher classifies
  every gap between consecutive device dispatches by cause —
  empty_queue (cond waits), host_serialize (result fetch/distribution
  and batch-formation host work), compile_stall, failover_backoff
  (device marked down) — into
  ``oryx_device_idle_gap_seconds{cause}``, turning "the device idles
  99%" (MFU 0.0091 at 1M×50f) into a ranked list of culprits.
  Residue the dispatcher cannot pin (more than ~10% of a gap and more
  than 2ms) is reported honestly as ``unattributed`` rather than
  silently folded.

- **XLA compile telemetry.** The batcher reports every first-dispatch
  compile of a new shape signature (k-bucket × padded batch × model
  generation) into ``oryx_xla_compile_seconds{kind}`` /
  ``oryx_xla_compiles_total{kind}``, marks the stall as a
  ``batcher.compile_stall`` trace span, and this module fires a
  ``compile-storm`` flight event when the recompile rate within the
  rolling window crosses ``oryx.monitoring.perfattr.compile-storm.
  threshold`` — the classic silent killer of a capacity-ladder batcher
  after a generation swap.

- **Burn-triggered profile capture.** When the serving-latency SLO's
  fast burn rate (common/slo.py) crosses ``burn-capture.
  burn-threshold``, a one-shot daemon thread captures a bounded
  profile window (perfstats ring summary + the live phase budget +
  optional jax.profiler trace dir) and records it as a
  ``profile-capture`` event in the on-disk flight ring — so a replica
  SIGKILLed while burning leaves a profile corpse the supervisor
  harvests. The check itself is a timestamp-gated float compare on the
  request flush path; the SLO trackers are scrape-driven and cheap to
  read directly.

Like perfstats, the ledger/stamp path is always on — there is no off
switch to forget, and the disabled cost a switch would save is a few
list appends per request. ``oryx.monitoring.perfattr.enabled = false``
only disables the *derived* machinery (storm events, burn capture,
budget windows), never the raw histograms.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from oryx_tpu.common.metrics import exponential_buckets, get_registry
from oryx_tpu.common.tracing import get_tracer

# Canonical request phases, in hot-path order. The metric label value is
# the tuple entry verbatim; docs/observability.md's "Reading the latency
# budget" section lists the same vocabulary.
PHASES = (
    "parse",          # socket read -> parsed request, + routing/query build
    "auth",           # credential check
    "queue_wait",     # batcher enqueue -> picked into a dispatch batch
    "batch_wait",     # picked -> its coalesced group starts forming
    "pad",            # group formation: pad-to-capacity matrix fill
    "device",         # device dispatch issue -> results fetched to host
    "host_fallback",  # scored on host after device error/wedge/shed-path
    "serialize",      # response object -> wire payload bytes
    "write",          # payload bytes -> socket
)

# Device idle-gap causes. `unattributed` is the honesty valve: time the
# dispatcher cannot pin on a concrete cause is reported, not hidden.
IDLE_CAUSES = (
    "empty_queue",
    "host_serialize",
    "compile_stall",
    "failover_backoff",
    "unattributed",
)

# Phase durations: 10us (a warm auth check) up to ~10s (a cold-compile
# device phase).
PHASE_SECONDS_BUCKETS = exponential_buckets(1e-5, 4.0, 10)

# Idle gaps: 100us up to ~26s (a compile stall or probe backoff window).
IDLE_GAP_BUCKETS = exponential_buckets(1e-4, 4.0, 10)

# Compile times: 1ms up to ~4 minutes (remote TPU compile worst case).
COMPILE_SECONDS_BUCKETS = exponential_buckets(1e-3, 4.0, 10)

DEFAULT_WINDOW_S = 60.0
DEFAULT_STORM_THRESHOLD = 6
DEFAULT_STORM_WINDOW_S = 60.0
DEFAULT_BURN_THRESHOLD = 14.0
DEFAULT_CAPTURE_S = 1.0
DEFAULT_MIN_INTERVAL_S = 300.0
DEFAULT_CHECK_INTERVAL_S = 5.0

# Gap residue at most this absolute size OR this fraction of the gap is
# dispatcher loop overhead (pick/group bookkeeping between timestamps) —
# folded into host_serialize; anything larger is unattributed.
_FOLD_ABS_S = 0.002
_FOLD_FRAC = 0.10


class PhaseLedger:
    """Per-request phase stamp accumulator.

    One ledger rides each Request end to end (``Request.ledger`` plus a
    thread-local mirror so the batcher can pick it up without threading
    it through every signature). ``add`` is a GIL-atomic list append —
    stamps may come from the frontend thread, the executor thread, and
    the batcher dispatcher; no lock needed. Flushed exactly once by the
    frontend after the response bytes hit the socket."""

    __slots__ = ("t0", "trace", "trace_id", "_items", "_flushed")

    def __init__(self, trace=None, trace_id: str | None = None):
        self.t0 = time.monotonic()
        self.trace = trace            # root Span (None when tracing off)
        self.trace_id = trace_id or (
            getattr(trace, "trace_id", None) if trace is not None else None
        )
        self._items: list[tuple[str, float, float]] = []
        self._flushed = False

    def add(self, phase: str, seconds: float, start: float | None = None) -> None:
        """Stamp ``seconds`` spent in ``phase`` (monotonic ``start`` when
        the caller has one — enables the trace waterfall span)."""
        if seconds < 0.0 or seconds != seconds:  # negative or NaN clock skew
            return
        self._items.append((phase, -1.0 if start is None else start, seconds))

    def items(self) -> list[tuple[str, float, float]]:
        return list(self._items)

    def total(self) -> float:
        return sum(s for _, _, s in self._items)

    def last_end(self) -> float | None:
        """Monotonic end of the latest stamped phase (None when no stamp
        carries a start). The serialize stamp anchors here so the slice
        between the last attributed phase and response rendering — result
        distribution, post-processing pool handoff, top-n trim — is
        charged to serialize instead of silently vanishing from the
        budget (the >=95% wall-clock coverage contract)."""
        ends = [st + s for _, st, s in self._items if st >= 0.0]
        return max(ends) if ends else None


_tls = threading.local()


def current_ledger() -> PhaseLedger | None:
    return getattr(_tls, "ledger", None)


def swap_ledger(ledger: PhaseLedger | None) -> PhaseLedger | None:
    """Install ``ledger`` as this thread's current ledger, returning the
    previous one (the tracing swap_current idiom — callers restore in a
    finally)."""
    prev = getattr(_tls, "ledger", None)
    _tls.ledger = ledger
    return prev


class PerfAttr:
    """Process-wide latency-attribution accounting: phase histograms +
    rolling budget window, idle-gap and compile telemetry, compile-storm
    detection, and the burn-triggered profile capture watcher."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S):
        self.enabled = True
        self.window_s = float(window_s)
        # rolling stamp windows backing budget(): (t_end, key, seconds)
        self._phase_win: deque[tuple[float, str, float]] = deque()
        self._gap_win: deque[tuple[float, str, float]] = deque()
        self._win_lock = threading.Lock()
        # compile-storm detection
        self.storm_threshold = DEFAULT_STORM_THRESHOLD
        self.storm_window_s = DEFAULT_STORM_WINDOW_S
        self._compiles: deque[float] = deque()   # guarded-by: _win_lock
        # burn-triggered capture
        self.burn_capture_enabled = True
        self.burn_threshold = DEFAULT_BURN_THRESHOLD
        self.capture_s = DEFAULT_CAPTURE_S
        self.min_interval_s = DEFAULT_MIN_INTERVAL_S
        self.check_interval_s = DEFAULT_CHECK_INTERVAL_S
        self._next_burn_check = 0.0
        self._burn_cooldown_until = 0.0
        self._burn_lock = threading.Lock()
        self._register_lock = threading.Lock()
        self.ensure_metrics()

    # -- configuration -----------------------------------------------------

    def configure(self, config) -> None:
        """Adopt the oryx.monitoring.perfattr.* keys (each layer runtime
        calls this at construction; last writer wins, the one-config-
        per-process convention)."""
        self.enabled = config.get_bool("oryx.monitoring.perfattr.enabled", True)
        self.window_s = float(config.get_float(
            "oryx.monitoring.perfattr.window-sec", DEFAULT_WINDOW_S
        ))
        self.storm_threshold = config.get_int(
            "oryx.monitoring.perfattr.compile-storm.threshold",
            DEFAULT_STORM_THRESHOLD,
        )
        self.storm_window_s = float(config.get_float(
            "oryx.monitoring.perfattr.compile-storm.window-sec",
            DEFAULT_STORM_WINDOW_S,
        ))
        self.burn_capture_enabled = config.get_bool(
            "oryx.monitoring.perfattr.burn-capture.enabled", True
        )
        self.burn_threshold = float(config.get_float(
            "oryx.monitoring.perfattr.burn-capture.burn-threshold",
            DEFAULT_BURN_THRESHOLD,
        ))
        self.capture_s = float(config.get_float(
            "oryx.monitoring.perfattr.burn-capture.capture-sec",
            DEFAULT_CAPTURE_S,
        ))
        self.min_interval_s = float(config.get_float(
            "oryx.monitoring.perfattr.burn-capture.min-interval-sec",
            DEFAULT_MIN_INTERVAL_S,
        ))
        self.check_interval_s = float(config.get_float(
            "oryx.monitoring.perfattr.burn-capture.check-interval-sec",
            DEFAULT_CHECK_INTERVAL_S,
        ))
        self.ensure_metrics()

    # -- request flush -----------------------------------------------------

    def observe_request(self, ledger: PhaseLedger | None) -> None:
        """Flush one request's ledger: phase histograms (+exemplars), the
        rolling budget window, the trace waterfall's phase.* child
        spans, and a timestamp-gated burn check. Idempotent per ledger —
        the Deferred/sync response paths can both reach the frontend's
        flush site."""
        if ledger is None or ledger._flushed:
            return
        ledger._flushed = True
        items = ledger.items()
        if not items:
            return
        now = time.monotonic()
        for phase, start, seconds in items:
            self._h_phase.observe(
                seconds, trace_id=ledger.trace_id, phase=phase
            )
        if self.enabled:
            with self._win_lock:
                self._prune(self._phase_win, now)
                for phase, start, seconds in items:
                    self._phase_win.append((now, phase, seconds))
        tr = get_tracer()
        if tr.enabled and ledger.trace is not None:
            for phase, start, seconds in items:
                if start >= 0.0:
                    tr.record_interval(
                        f"phase.{phase}", start, start + seconds,
                        parent=ledger.trace,
                    )
        self._maybe_burn_check(now)

    # -- idle gaps ---------------------------------------------------------

    def record_idle_gap(self, cause: str, seconds: float) -> None:
        """One classified slice of device idle time (dispatcher thread)."""
        if seconds <= 0.0 or seconds != seconds:
            return
        self._h_gap.observe(seconds, cause=cause)
        if self.enabled:
            now = time.monotonic()
            with self._win_lock:
                self._prune(self._gap_win, now)
                self._gap_win.append((now, cause, seconds))

    # -- compile telemetry -------------------------------------------------

    def record_compile(self, kind: str, seconds: float) -> None:
        """One first-dispatch XLA compile of a new shape signature. Feeds
        the per-kind histogram/counter and the storm detector."""
        self._c_compile.inc(kind=kind)
        self._h_compile.observe(max(0.0, seconds), kind=kind)
        if not self.enabled:
            return
        now = time.monotonic()
        storm = 0
        with self._win_lock:
            dq = self._compiles
            dq.append(now)
            cutoff = now - self.storm_window_s
            while dq and dq[0] < cutoff:
                dq.popleft()
            if self.storm_threshold > 0 and len(dq) >= self.storm_threshold:
                storm = len(dq)
        if storm:
            from oryx_tpu.common.flightrec import get_flightrec

            # episode-limited: a sustained storm records one event per
            # window, not one per recompile
            get_flightrec().record(
                kind="compile-storm",
                episode_s=self.storm_window_s,
                compiles=storm,
                window_s=self.storm_window_s,
                dispatch_kind=kind,
                last_compile_s=round(seconds, 4),
            )

    # -- reading -----------------------------------------------------------

    def _prune(self, dq, now: float) -> None:  # oryxlint: holds=_win_lock
        cutoff = now - self.window_s
        while dq and dq[0][0] < cutoff:
            dq.popleft()

    def budget(self) -> dict:
        """Per-window latency budget: per-phase p50/p99/share plus the
        ranked idle-gap causes. The /healthz section the fleet front
        federates, and the substrate of `oryx perf`'s local view."""
        now = time.monotonic()
        with self._win_lock:
            self._prune(self._phase_win, now)
            self._prune(self._gap_win, now)
            phase_items = list(self._phase_win)
            gap_items = list(self._gap_win)
        by_phase: dict[str, list[float]] = {}
        for _, phase, s in phase_items:
            by_phase.setdefault(phase, []).append(s)
        total = sum(s for _, _, s in phase_items)
        phases = {}
        for phase in PHASES:
            vals = by_phase.pop(phase, None)
            if not vals:
                continue
            vals.sort()
            phases[phase] = {
                "count": len(vals),
                "p50_ms": round(_quantile(vals, 0.50) * 1e3, 3),
                "p99_ms": round(_quantile(vals, 0.99) * 1e3, 3),
                "share": round(sum(vals) / total, 4) if total > 0 else 0.0,
            }
        for phase, vals in by_phase.items():  # stamps outside the catalog
            vals.sort()
            phases[phase] = {
                "count": len(vals),
                "p50_ms": round(_quantile(vals, 0.50) * 1e3, 3),
                "p99_ms": round(_quantile(vals, 0.99) * 1e3, 3),
                "share": round(sum(vals) / total, 4) if total > 0 else 0.0,
            }
        gap_total = sum(s for _, _, s in gap_items)
        gaps: dict[str, float] = {}
        for _, cause, s in gap_items:
            gaps[cause] = gaps.get(cause, 0.0) + s
        idle = {
            cause: {
                "seconds": round(s, 4),
                "share": round(s / gap_total, 4) if gap_total > 0 else 0.0,
            }
            for cause, s in sorted(
                gaps.items(), key=lambda kv: kv[1], reverse=True
            )
        }
        return {
            "window_seconds": self.window_s,
            "total_phase_seconds": round(total, 4),
            "phases": phases,
            "idle_gaps": idle,
        }

    def healthz_section(self) -> dict:
        return self.budget()

    # -- burn-triggered capture --------------------------------------------

    def _maybe_burn_check(self, now: float) -> None:
        """Timestamp-gated fast-burn probe on the request flush path: one
        float compare per request, a real SLO read at most every
        check-interval-sec, a capture at most every min-interval-sec."""
        if not (self.enabled and self.burn_capture_enabled):
            return
        if now < self._next_burn_check:
            return
        with self._burn_lock:
            if now < self._next_burn_check:
                return
            self._next_burn_check = now + self.check_interval_s
            if now < self._burn_cooldown_until:
                return
            burn = _latency_fast_burn()
            if burn is None or burn < self.burn_threshold:
                return
            self._burn_cooldown_until = now + self.min_interval_s
        t = threading.Thread(
            target=self._burn_capture, args=(burn,),
            name="oryx-burn-capture", daemon=True,
        )
        t.start()

    def _burn_capture(self, burn: float) -> None:
        """Capture a bounded profile window and leave it in the flight
        ring (the on-disk ring survives a SIGKILL — the corpse the
        supervisor harvests names where the time went)."""
        from oryx_tpu.common.flightrec import get_flightrec
        from oryx_tpu.common.perfstats import get_perfstats

        meta = None
        try:
            prof = get_perfstats().capture_profile(max(0.0, self.capture_s))
            meta = prof.get("oryx")
        except RuntimeError:
            meta = {"skipped": "a profile capture was already running"}
        except Exception as e:  # noqa: BLE001 - capture must never kill serving
            meta = {"error": str(e)}
        get_flightrec().record(
            kind="profile-capture",
            trigger="latency-fast-burn",
            burn_rate=round(burn, 2),
            budget=self.budget(),
            profile=meta,
        )

    # -- metrics -----------------------------------------------------------

    def ensure_metrics(self) -> None:
        """Register the attribution families on the global registry (safe
        to call repeatedly; rebinding over the singleton keeps series
        alive across registry.clear() in tests)."""
        reg = get_registry()
        with self._register_lock:
            self._h_phase = reg.histogram(
                "oryx_request_phase_seconds",
                "Per-request time in each hot-path phase (parse, auth, "
                "queue_wait, batch_wait, pad, device, host_fallback, "
                "serialize, write), by phase; carries metric->trace "
                "exemplars when tracing is enabled",
                buckets=PHASE_SECONDS_BUCKETS,
            )
            self._h_gap = reg.histogram(
                "oryx_device_idle_gap_seconds",
                "Gaps between consecutive device dispatches classified "
                "by cause (empty_queue, host_serialize, compile_stall, "
                "failover_backoff, unattributed), by cause",
                buckets=IDLE_GAP_BUCKETS,
            )
            self._h_compile = reg.histogram(
                "oryx_xla_compile_seconds",
                "First-dispatch XLA compile time per new shape signature "
                "(k-bucket x padded batch x model generation), by kind",
                buckets=COMPILE_SECONDS_BUCKETS,
            )
            self._c_compile = reg.counter(
                "oryx_xla_compiles_total",
                "XLA compilations observed (first device dispatch of a "
                "new shape signature), by kind; the compile-storm flight "
                "event fires when the windowed rate crosses the "
                "configured threshold",
                labeled=True,
            )


def classify_idle_gap(
    gap: float,
    wait_s: float = 0.0,
    serialize_s: float = 0.0,
    down_s: float = 0.0,
) -> dict[str, float]:
    """Split one inter-dispatch idle gap into cause → seconds.

    The dispatcher measures what it can directly — condition-variable
    wait time (``wait_s`` → empty_queue), host result fetch/distribution
    time (``serialize_s`` → host_serialize), and device-down backoff
    (``down_s`` → failover_backoff) — each capped at what the gap can
    still hold, in that order. Residue up to max(2ms, 10% of the gap) is
    dispatcher loop overhead between the measured timestamps
    (pick/group/pad bookkeeping — host work by definition) and folds
    into host_serialize; anything larger is reported honestly as
    unattributed. Compile stalls are recorded separately at the dispatch
    call site, where the compile is actually observed."""
    out: dict[str, float] = {}
    if gap <= 1e-6:
        return out
    wait_s = min(max(0.0, wait_s), gap)
    down_s = min(max(0.0, down_s), gap - wait_s)
    serialize_s = min(max(0.0, serialize_s), gap - wait_s - down_s)
    rem = gap - wait_s - down_s - serialize_s
    if rem <= max(_FOLD_ABS_S, _FOLD_FRAC * gap):
        serialize_s += rem
        rem = 0.0
    if wait_s > 0.0:
        out["empty_queue"] = wait_s
    if serialize_s > 0.0:
        out["host_serialize"] = serialize_s
    if down_s > 0.0:
        out["failover_backoff"] = down_s
    if rem > 0.0:
        out["unattributed"] = rem
    return out


def _quantile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank quantile of an already-sorted non-empty list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


def _latency_fast_burn() -> float | None:
    """The serving-latency SLO's fast-window burn rate, or None when the
    tracker is not registered (non-serving processes)."""
    from oryx_tpu.common.slo import current_burn

    return current_burn("serving-latency")


_default = PerfAttr()


def get_perfattr() -> PerfAttr:
    return _default


def configure_perfattr(config) -> PerfAttr:
    _default.configure(config)
    return _default
