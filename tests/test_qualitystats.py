"""Live model-quality observability (ISSUE 15): shadow rescore
sampling, drift detection, per-generation scorecards, the quality SLO,
and the degraded-model chaos loop.

The acceptance shape: a corrupted generation must drop the MEASURED
live recall below the floor, burn the quality SLO, and land a
quality-alarm flight event with the generation id — while sampling
stays provably off the request path (a saturated shadow queue drops
samples, never requests)."""

from __future__ import annotations

import json
import math
import time

import numpy as np
import pytest

from oryx_tpu.common.config import load_config
from oryx_tpu.common.metrics import get_registry
from oryx_tpu.common.qualitystats import (
    QualityStats,
    TrainingProfile,
    build_training_profile,
    sketch_of,
)


def _qs(**overlay) -> QualityStats:
    cfg = load_config(overlay={
        "oryx.monitoring.quality.sample-rate": 1.0,
        "oryx.monitoring.quality.window-sec": 60,
        **overlay,
    })
    qs = QualityStats()
    qs.configure(cfg)
    return qs


def _corpus(n=64, f=8, seed=0):
    rng = np.random.default_rng(seed)
    mat = rng.standard_normal((n, f)).astype(np.float32)
    ids = [f"i{j}" for j in range(n)]
    return mat, ids


def _served(mat, ids, vec, k=10):
    scores = mat @ vec
    order = np.argsort(-scores)[:k]
    return [(ids[int(j)], float(scores[j])) for j in order]


# ---- shadow rescore sampling ------------------------------------------------


def test_shadow_sample_exact_answer_scores_recall_one():
    qs = _qs()
    mat, ids = _corpus()
    vec = np.random.default_rng(1).standard_normal(8).astype(np.float32)
    assert qs.maybe_sample(
        vec, _served(mat, ids, vec), how_many=10,
        score_mode="exact", snapshot_fn=lambda: (mat, ids, len(ids)),
    )
    assert qs.flush(10)
    assert qs.live_recall() == pytest.approx(1.0)
    assert qs.live_recall("exact") == pytest.approx(1.0)
    # an unseen mode's window is empty -> NaN, never a confident number
    assert math.isnan(qs.live_recall("quantized"))


def test_shadow_sample_wrong_answer_counts_bad_and_margin():
    qs = _qs()
    mat, ids = _corpus()
    vec = np.random.default_rng(2).standard_normal(8).astype(np.float32)
    exact = _served(mat, ids, vec)
    worst = exact[-1][1] - 10.0  # served scores far below the true top
    wrong = [(i, worst) for i, _ in _served(mat, ids, -vec)]
    c_bad = get_registry().counter("oryx_quality_bad_samples_total")
    bad_before = sum(c_bad.series().values())
    qs.maybe_sample(
        vec, wrong, how_many=10, score_mode="quantized",
        snapshot_fn=lambda: (mat, ids, len(ids)),
    )
    assert qs.flush(10)
    r = qs.live_recall("quantized")
    assert r < 0.5
    assert sum(c_bad.series().values()) == bad_before + 1
    # margin: the approximation gave up real score -> lands off the 0 bucket
    h = get_registry().histogram("oryx_live_score_margin")
    assert h.count() >= 1


def test_shadow_recall_respects_exclusions():
    """The exact reference applies the SAME exclusion trim serving did:
    a served page that correctly skipped excluded ids must score 1.0,
    not be penalized for missing them."""
    qs = _qs()
    mat, ids = _corpus()
    vec = np.random.default_rng(3).standard_normal(8).astype(np.float32)
    full = _served(mat, ids, vec, k=14)
    exclude = {full[0][0], full[2][0]}
    served = [(i, s) for i, s in full if i not in exclude][:10]
    qs.maybe_sample(
        vec, served, how_many=10, exclude=exclude, score_mode="exact",
        snapshot_fn=lambda: (mat, ids, len(ids)),
    )
    assert qs.flush(10)
    assert qs.live_recall() == pytest.approx(1.0)


def test_saturated_queue_drops_samples_never_blocks():
    qs = _qs(**{"oryx.monitoring.quality.max-queue": 2})
    mat, ids = _corpus()
    vec = np.random.default_rng(4).standard_normal(8).astype(np.float32)
    served = _served(mat, ids, vec)
    drops = get_registry().counter("oryx_quality_sample_drops_total")
    before = drops.value()
    qs.drain_gate.set()  # park the drain: the burst must overflow
    try:
        t0 = time.monotonic()
        accepted = sum(
            qs.maybe_sample(
                vec, served, how_many=10,
                snapshot_fn=lambda: (mat, ids, len(ids)),
            )
            for _ in range(20)
        )
        elapsed = time.monotonic() - t0
    finally:
        qs.drain_gate.clear()
    assert accepted <= 3  # queue bound (+ at most one in the drain's hand)
    assert drops.value() - before >= 17
    assert elapsed < 1.0  # put_nowait never blocked
    assert qs.flush(10)


def test_sampler_off_is_free():
    qs = _qs(**{"oryx.monitoring.quality.sample-rate": 0.0})
    mat, ids = _corpus()
    assert not qs.maybe_sample(
        np.zeros(8, np.float32), [("i0", 1.0)], how_many=10,
        snapshot_fn=lambda: (mat, ids, len(ids)),
    )
    assert qs.samples_processed() == 0


# ---- OpenMetrics round-trip -------------------------------------------------


def test_quality_families_openmetrics_roundtrip_with_exemplar():
    """Every new family renders through the strict OpenMetrics reference
    parser, and the recall-margin histogram carries a trace exemplar."""
    parser = pytest.importorskip("prometheus_client.openmetrics.parser")
    qs = _qs()
    mat, ids = _corpus()
    vec = np.random.default_rng(5).standard_normal(8).astype(np.float32)
    qs.maybe_sample(
        vec, _served(mat, ids, vec), how_many=10, score_mode="exact",
        trace_id="abc123def4567890", snapshot_fn=lambda: (mat, ids, len(ids)),
    )
    assert qs.flush(10)
    qs.note_catalog(ids)
    qs.set_training_profile(
        build_training_profile(ids, np.ones(len(ids)), scores=mat @ vec)
    )
    qs.note_input_events(ids[:16], np.arange(16) * 1000)
    # force the scorecard + SLO-error families to exist regardless of
    # test ordering (process-global registry)
    from oryx_tpu.common import slo
    from oryx_tpu.common.freshness import model_freshness

    model_freshness()
    slo._sample_errors()
    text = get_registry().render_prometheus(openmetrics=True)
    families = {
        f.name: f for f in parser.text_string_to_metric_families(text)
    }
    for name in (
        "oryx_live_recall_at_k",
        "oryx_live_score_margin",
        "oryx_quality_samples",
        "oryx_quality_bad_samples",
        "oryx_quality_sample_drops",
        "oryx_input_drift",
        "oryx_prediction_drift",
        "oryx_generation_quality",
        "oryx_slo_sample_errors",
    ):
        assert name in families, f"{name} missing from OpenMetrics page"
    margins = families["oryx_live_score_margin"]
    exemplars = [
        s.exemplar for s in margins.samples
        if s.name.endswith("_bucket") and s.exemplar is not None
    ]
    assert exemplars, "recall-margin histogram lost its trace exemplar"
    assert exemplars[0].labels["trace_id"] == "abc123def4567890"


# ---- training profile + drift ----------------------------------------------


def test_training_profile_roundtrips_and_sketch_is_normalized():
    ids = [f"i{j}" for j in range(100)]
    p = build_training_profile(
        ids, np.arange(100) + 1.0,
        timestamps_ms=np.arange(1_000, 101_000, 1_000),
        prev_item_ids=ids[:50],
        scores=np.random.default_rng(0).standard_normal(64),
    )
    q = TrainingProfile.from_json(p.to_json())
    assert q.events_per_sec == pytest.approx(p.events_per_sec)
    assert q.new_item_fraction == pytest.approx(0.5)
    assert q.score_mean == pytest.approx(p.score_mean)
    assert sum(q.item_sketch) == pytest.approx(1.0, abs=1e-3)
    assert sketch_of([]).sum() == 0.0  # empty window: zeros, not NaN


def test_drift_signals_move_with_distribution_shift(tmp_path):
    from oryx_tpu.common import flightrec

    rec = flightrec.get_flightrec()
    rec.dir = str(tmp_path)
    rec.enabled = True
    with rec._lock:
        # the global recorder's episode rate-limit may still be armed by
        # an earlier test's drift-alarm (the e2e suites publish profiled
        # generations); this test must observe ITS alarm
        rec._last_episode.pop("drift-alarm", None)
    qs = _qs(**{"oryx.monitoring.quality.drift.alarm-threshold": 0.4})
    ids = [f"i{j}" for j in range(200)]
    qs.set_training_profile(
        build_training_profile(ids, np.ones(200), scores=np.zeros(8))
    )
    qs.note_catalog(ids)
    # same shape as training -> near-zero popularity drift
    qs.note_input_events(ids)
    low = qs.input_drift("item-popularity")
    assert low == pytest.approx(0.0, abs=0.05)
    assert qs.input_drift("new-item-fraction") == 0.0
    # a hot-item storm on an unseen item: the popularity sketch
    # concentrates into one bucket (shape shift, what the TV distance
    # detects) and every event is on an item the model never trained on
    alien = ["alien-hot"] * 400
    qs.note_input_events(alien)
    assert qs.input_drift("item-popularity") > 0.4
    assert qs.input_drift("new-item-fraction") > 0.5
    events = [
        e for e in flightrec.read_events(str(tmp_path))
        if e.get("kind") == "drift-alarm"
    ]
    assert events, "drift past the threshold recorded no drift-alarm"
    assert events[-1]["signal"].startswith(("input:", "prediction:"))


def test_drift_is_nan_without_profile_or_window():
    qs = _qs()
    assert math.isnan(qs.input_drift("item-popularity"))
    assert math.isnan(qs.prediction_drift("score-mean"))
    qs.set_training_profile(TrainingProfile(item_sketch=[1.0] * 4))
    assert math.isnan(qs.input_drift("item-popularity"))  # no live window


def test_als_artifact_carries_profile_and_serving_adopts_it():
    """ALSUpdate stamps qualityProfile into the artifact; the serving
    state's MODEL apply hands it to the live tracker."""
    import oryx_tpu.common.qualitystats as qmod
    from oryx_tpu.common.artifact import ModelArtifact
    from oryx_tpu.ops.als import InteractionData
    from oryx_tpu.apps.als.batch import ALSUpdate
    from oryx_tpu.apps.als.state import apply_update_message

    cfg = load_config(overlay={"oryx.id": "qprof"})
    upd = ALSUpdate(cfg, mesh=None)
    upd._window_tss = np.arange(10_000, 20_000, 100)
    rng = np.random.default_rng(0)
    n_u, n_i, f = 12, 20, 4
    agg = InteractionData(
        user_ids=[f"u{j}" for j in range(n_u)],
        item_ids=[f"i{j}" for j in range(n_i)],
        users=rng.integers(0, n_u, 100).astype(np.int32),
        items=rng.integers(0, n_i, 100).astype(np.int32),
        values=np.ones(100, np.float32),
    )

    class M:
        x = rng.standard_normal((n_u, f)).astype(np.float32)
        y = rng.standard_normal((n_i, f)).astype(np.float32)
        user_ids = agg.user_ids
        item_ids = agg.item_ids

    art = upd._artifact_from_model(
        M, {"features": f, "lambda": 0.1, "alpha": 1.0}, agg
    )
    prof_json = art.get_extension("qualityProfile")
    assert prof_json, "artifact lacks the qualityProfile extension"
    prof = TrainingProfile.from_json(prof_json)
    assert prof.events_per_sec and prof.events_per_sec > 0
    assert prof.score_mean is not None

    # serving adoption: apply the artifact as a MODEL message and the
    # process tracker must hold the same profile + catalog
    prev = qmod._default
    qmod._default = QualityStats()
    try:
        apply_update_message(None, "MODEL", art.to_string())
        adopted = qmod._default.profile
        assert adopted is not None
        assert adopted.item_sketch == pytest.approx(prof.item_sketch)
        with qmod._default._lock:
            assert qmod._default._known_items == set(agg.item_ids)
    finally:
        qmod._default = prev


# ---- scorecards --------------------------------------------------------------


def test_publish_stamp_quality_feeds_gauge_and_freshness():
    from oryx_tpu.common.freshness import model_freshness, publish_stamp

    mf = model_freshness()
    mf.note_loaded("MODEL", "m1")
    mf.note_stamp(publish_stamp(generation=777, quality={"auc": 0.87}))
    assert mf.generation == 777
    assert mf.quality == {"auc": 0.87}
    g = get_registry().gauge("oryx_generation_quality")
    assert g.value(metric="auc") == pytest.approx(0.87)
    # a card-less generation must not keep exporting its predecessor's
    # scorecard under "currently served"
    mf.note_loaded("MODEL", "m2")
    mf.note_stamp(publish_stamp(generation=778))
    assert mf.quality is None
    assert g.value(metric="auc") == 0.0  # series dropped


def test_generation_swap_resets_live_sample_windows():
    """A new generation's adoption clears the shadow recall/score
    windows: a healthy rollback must never inherit (and be alarmed for)
    the corrupted predecessor's bad samples."""
    import oryx_tpu.common.qualitystats as qmod
    from oryx_tpu.common.freshness import model_freshness, publish_stamp

    qs = _qs()
    mat, ids = _corpus()
    vec = np.random.default_rng(9).standard_normal(8).astype(np.float32)
    qs.maybe_sample(
        vec, _served(mat, ids, vec), how_many=10,
        snapshot_fn=lambda: (mat, ids, len(ids)),
    )
    assert qs.flush(10)
    assert qs.live_recall() == pytest.approx(1.0)
    prev = qmod._default
    qmod._default = qs
    try:
        mf = model_freshness()
        mf.note_loaded("MODEL", "m-reset")
        mf.note_stamp(publish_stamp(generation=999))
    finally:
        qmod._default = prev
    assert math.isnan(qs.live_recall())  # window is generation-scoped


def test_mlupdate_note_eval_rides_the_stamp():
    from oryx_tpu.apps.als.batch import ALSUpdate

    cfg = load_config(overlay={"oryx.id": "stampq"})
    upd = ALSUpdate(cfg, mesh=None)
    assert upd.eval_metric_name() == "auc"  # implicit default
    upd.note_eval(0.91)

    sent = []

    class P:
        def send(self, key, msg):
            sent.append((key, msg))

    upd.send_publish_stamp("/models/123456", P())
    (key, msg), = sent
    assert key == "TRACE"
    stamp = json.loads(msg)
    assert stamp["generation"] == 123456
    assert stamp["quality"] == {"auc": 0.91}
    # a NaN eval clears the card instead of stamping a lie
    upd.note_eval(float("nan"))
    upd.send_publish_stamp("/models/123457", P())
    assert "quality" not in json.loads(sent[-1][1])


# ---- quality SLO + sample-error satellite -----------------------------------


def test_quality_slo_burns_on_bad_samples():
    from oryx_tpu.common import slo

    cfg = load_config(overlay={
        "oryx.monitoring.slo.fast-window-sec": 60,
        "oryx.monitoring.slo.quality.objective": 0.95,
    })
    slo.ensure_quality_slo(cfg)
    t = slo.tracker("quality")
    assert t is not None
    t.burn_rate(t.fast_s)  # baseline ring sample
    time.sleep(slo._MIN_SAMPLE_GAP_S + 0.02)
    c_all = get_registry().counter("oryx_quality_samples_total")
    c_bad = get_registry().counter("oryx_quality_bad_samples_total")
    for _ in range(20):
        c_all.inc(score_mode="quantized")
        c_bad.inc(score_mode="quantized")
    assert t.burn_rate(t.fast_s) > 5  # all-bad burns far past the page line


def test_slo_sample_errors_counted_and_surfaced():
    from oryx_tpu.common import slo

    c = get_registry().counter("oryx_slo_sample_errors_total")
    before = c.value(slo="broken-source")

    def exploding():
        raise RuntimeError("metric renamed out from under the SLO")

    t = slo.SloTracker("broken-source", 0.99, exploding, 1.0, 2.0)
    with slo._trackers_lock:
        slo._trackers["broken-source"] = t
    try:
        assert t.burn_rate(t.fast_s) == 0.0  # never raises out
        assert c.value(slo="broken-source") == before + 1
        assert "metric renamed" in t.last_error
        assert "broken-source" in slo.sample_errors()
    finally:
        with slo._trackers_lock:
            slo._trackers.pop("broken-source", None)


# ---- serving surfaces -------------------------------------------------------


def _als_model_message(gen: int, corrupted: bool = False) -> str:
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent))
    from tools.chaos import _quality_model_message

    return _quality_model_message(gen, corrupted)


def test_healthz_quality_section_and_console_row():
    from oryx_tpu.serving.app import Request, ServingApp
    from oryx_tpu.apps.als.serving import ALSServingModelManager

    cfg = load_config(overlay={
        "oryx.id": "qhealthz",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.als",
        ],
        "oryx.monitoring.quality.sample-rate": 1.0,
    })
    manager = ALSServingModelManager(cfg)
    app = ServingApp(cfg, manager, input_producer=None)
    manager.consume_key_message("MODEL", _als_model_message(1))

    status, body, _ = app.dispatch(
        Request("GET", "/healthz", {}, {}, b"", {})
    )
    assert status == 200
    doc = json.loads(body)
    assert "quality" in doc
    q = doc["quality"]
    assert {"live_recall_at_10", "samples", "dropped", "sample_rate"} <= set(q)
    json.dumps(q)  # strictly JSON-finite

    status, body, _ = app.dispatch(
        Request("GET", "/console", {}, {}, b"", {})
    )
    assert status == 200
    assert b"live recall@10 (measured)" in body
    manager.close()


def test_fleet_status_carries_quality_and_slo_errors():
    from oryx_tpu.fleet.front import FleetFront

    cfg = load_config(overlay={"oryx.id": "qfleet"})
    front = FleetFront(
        cfg, backends=[("r0", "127.0.0.1", 18099)], port=0
    )
    front.replicas[0].quality = {"live_recall_at_10": 0.97, "samples": 12}
    status, body, ctype, _ = front._local_endpoint("GET", "/fleet/status")
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    assert "slo_errors" in doc
    assert doc["replicas"][0]["quality"] == {
        "live_recall_at_10": 0.97, "samples": 12
    }


# ---- cli flight --kind ------------------------------------------------------


def test_cli_flight_kind_filter(tmp_path, capsys):
    from oryx_tpu.common.flightrec import FlightRecorder
    from oryx_tpu.cli import main as cli_main

    rec = FlightRecorder()
    rec.dir = str(tmp_path)
    rec.record(kind="ejection", replica="r0")
    rec.record(kind="generation", generation=5)
    rec.record(kind="quality-alarm", generation=5, live_recall=0.1)

    rc = cli_main([
        "flight", "--kind", "quality-alarm", "--kind", "ejection",
        "--set", f"oryx.monitoring.flight.dir={tmp_path}",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    kinds = [json.loads(line)["kind"] for line in out]
    assert kinds == ["ejection", "quality-alarm"]

    # unknown kinds fail loudly instead of printing an empty ring
    rc = cli_main([
        "flight", "--kind", "no-such-kind",
        "--set", f"oryx.monitoring.flight.dir={tmp_path}",
    ])
    assert rc == 2


# ---- the end-to-end acceptance loop -----------------------------------------


def test_chaos_degraded_model_scenario(tmp_path):
    """Corrupted generation -> live recall collapse -> quality SLO fast
    burn -> quality-alarm flight event with the generation id, with
    sampling provably off the request path (tools/chaos.py
    degraded-model, run in-process)."""
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent))
    from tools.chaos import SCENARIOS

    _doc, fn = SCENARIOS["degraded-model"]
    problems = fn(str(tmp_path))
    assert problems == [], "\n".join(problems)
