"""Device mesh + sharding helpers (the distributed compute plane).

Replaces the reference's intra-job Spark plane (shuffle/broadcast/collect,
SURVEY.md §5 "Distributed communication backend") with XLA collectives over
ICI/DCN: arrays are laid out on a jax.sharding.Mesh and jit inserts
psum/all_gather where the sharded einsums demand them.
"""

from oryx_tpu.parallel.mesh import (
    MeshSpec,
    data_sharding,
    host_mesh,
    make_mesh,
    model_mesh,
    model_sharding,
    replicated,
    shard_array,
)
from oryx_tpu.parallel.shardspec import RowShards, shard_devices
