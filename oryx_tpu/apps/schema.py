"""Config-driven input schema + categorical value dictionaries.

Parity with the reference's app-common schema tier (app/oryx-app-common
.../schema/InputSchema.java:37-278 and CategoricalValueEncodings.java):
the schema names each CSV column and designates id / ignored / categorical /
numeric / target roles; predictors are the active non-target features, with
bidirectional feature-index <-> predictor-index maps. Encodings assign each
categorical feature a stable value <-> int dictionary so datums become
dense numeric rows — the form every jitted op consumes.

TPU-native twist: `encode_matrix` vectorizes whole datasets to float32
numpy (NaN for missing), the host-side step before device placement;
the reference encodes row-at-a-time into LabeledPoint/Example objects.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from oryx_tpu.common.config import Config


class InputSchema:
    def __init__(self, config: Config):
        names = list(config.get_list("oryx.input-schema.feature-names", []) or [])
        if not names:
            n = config.get_int("oryx.input-schema.num-features", 0)
            if n <= 0:
                raise ValueError("neither feature-names nor num-features is set")
            names = [str(i) for i in range(n)]
        if len(set(names)) != len(names):
            raise ValueError(f"feature names must be unique: {names}")
        self.feature_names: list[str] = names

        def name_set(key) -> set[str]:
            vals = set(map(str, config.get_list(key, []) or []))
            unknown = vals - set(names)
            if unknown:
                raise ValueError(f"{key} names unknown features: {sorted(unknown)}")
            return vals

        self.id_features = name_set("oryx.input-schema.id-features")
        ignored = name_set("oryx.input-schema.ignored-features")
        active = [n for n in names if n not in self.id_features and n not in ignored]
        self.active_features = set(active)

        # raw get(): a `null` in config must stay None (unset) — get_list
        # would coerce it to [], which is a *set but empty* designation
        numeric = config.get("oryx.input-schema.numeric-features", None)
        categorical = config.get("oryx.input-schema.categorical-features", None)
        if numeric is None and categorical is None:
            raise ValueError("neither numeric-features nor categorical-features set")
        if numeric is not None:
            self.numeric_features = set(map(str, numeric))
            if not self.numeric_features <= self.active_features:
                raise ValueError("numeric-features must be active features")
            self.categorical_features = self.active_features - self.numeric_features
        else:
            self.categorical_features = set(map(str, categorical))
            if not self.categorical_features <= self.active_features:
                raise ValueError("categorical-features must be active features")
            self.numeric_features = self.active_features - self.categorical_features

        target = config.get_string("oryx.input-schema.target-feature", None)
        if target is not None and target not in self.active_features:
            raise ValueError(f"target feature not active: {target}")
        self.target_feature = target
        self.target_index = names.index(target) if target else -1

        # feature index <-> predictor index (active, non-target)
        self._all_to_predictor: dict[int, int] = {}
        self._predictor_to_all: dict[int, int] = {}
        p = 0
        for i, n in enumerate(names):
            if n in self.active_features and i != self.target_index:
                self._all_to_predictor[i] = p
                self._predictor_to_all[p] = i
                p += 1

    # -- introspection (InputSchema.java accessors) -------------------------

    @property
    def num_features(self) -> int:
        return len(self.feature_names)

    @property
    def num_predictors(self) -> int:
        return len(self._all_to_predictor)

    def has_target(self) -> bool:
        return self.target_feature is not None

    def is_id(self, f: int | str) -> bool:
        return self._name(f) in self.id_features

    def is_active(self, f: int | str) -> bool:
        return self._name(f) in self.active_features

    def is_numeric(self, f: int | str) -> bool:
        return self._name(f) in self.numeric_features

    def is_categorical(self, f: int | str) -> bool:
        return self._name(f) in self.categorical_features

    def is_target(self, f: int | str) -> bool:
        return self.has_target() and self._name(f) == self.target_feature

    def is_classification(self) -> bool:
        return self.has_target() and self.is_categorical(self.target_feature)

    def feature_to_predictor_index(self, i: int) -> int:
        return self._all_to_predictor[i]

    def predictor_to_feature_index(self, p: int) -> int:
        return self._predictor_to_all[p]

    def _name(self, f: int | str) -> str:
        return self.feature_names[f] if isinstance(f, int) else f


class CategoricalValueEncodings:
    """Per-categorical-feature value <-> int dictionaries, built from data
    in sorted order for determinism (CategoricalValueEncodings.java)."""

    def __init__(self, distinct_values: dict[int, Iterable[str]]):
        self._value_to_code: dict[int, dict[str, int]] = {}
        self._code_to_value: dict[int, list[str]] = {}
        for fi, vals in distinct_values.items():
            ordered = sorted(set(map(str, vals)))
            self._value_to_code[fi] = {v: c for c, v in enumerate(ordered)}
            self._code_to_value[fi] = ordered

    @classmethod
    def from_data(
        cls, schema: InputSchema, rows: Sequence[Sequence[str]]
    ) -> "CategoricalValueEncodings":
        distinct: dict[int, set[str]] = {
            i: set()
            for i, n in enumerate(schema.feature_names)
            if schema.is_categorical(n)
        }
        for row in rows:
            for i in distinct:
                if i < len(row) and row[i] != "":
                    distinct[i].add(str(row[i]))
        return cls(distinct)

    def encode(self, feature_index: int, value: str) -> int:
        return self._value_to_code[feature_index][str(value)]

    def decode(self, feature_index: int, code: int) -> str:
        return self._code_to_value[feature_index][code]

    def get_value_count(self, feature_index: int) -> int:
        return len(self._code_to_value.get(feature_index, ()))

    def get_encoding_map(self, feature_index: int) -> dict[str, int]:
        return dict(self._value_to_code[feature_index])

    def get_values(self, feature_index: int) -> list[str]:
        return list(self._code_to_value[feature_index])

    def to_content(self) -> dict:
        """JSON-safe form for model-artifact embedding."""
        return {str(i): vals for i, vals in self._code_to_value.items()}

    @classmethod
    def from_content(cls, content: dict) -> "CategoricalValueEncodings":
        return cls({int(i): vals for i, vals in content.items()})


def encode_matrix(
    schema: InputSchema,
    encodings: CategoricalValueEncodings | None,
    rows: Sequence[Sequence[str]],
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorize parsed CSV rows -> (predictors [N,P] f32, target [N] f32).

    Categorical predictors/targets become their integer codes; missing or
    unknown values become NaN. Target is NaN-filled when the schema has
    none. This is the host-side analogue of RDFUpdate's LabeledPoint
    encoding (app/oryx-app-mllib .../rdf/RDFUpdate.java:228-262), done
    column-wise for the whole dataset.
    """
    n = len(rows)
    x = np.full((n, schema.num_predictors), np.nan, dtype=np.float32)
    t = np.full(n, np.nan, dtype=np.float32)
    for p in range(schema.num_predictors):
        fi = schema.predictor_to_feature_index(p)
        cat = schema.is_categorical(fi)
        for r, row in enumerate(rows):
            if fi >= len(row) or row[fi] == "":
                continue
            if cat:
                if encodings is None:
                    continue  # no dictionaries: categorical stays NaN
                try:
                    x[r, p] = encodings.encode(fi, row[fi])
                except KeyError:
                    pass
            else:
                try:
                    x[r, p] = float(row[fi])
                except ValueError:
                    pass
    if schema.has_target():
        ti = schema.target_index
        cat = schema.is_categorical(ti)
        for r, row in enumerate(rows):
            if ti >= len(row) or row[ti] == "":
                continue
            if cat:
                if encodings is None:
                    continue
                try:
                    t[r] = encodings.encode(ti, row[ti])
                except KeyError:
                    pass
            else:
                try:
                    t[r] = float(row[ti])
                except ValueError:
                    pass
    return x, t
