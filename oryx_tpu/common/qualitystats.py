"""Live model-quality observability: shadow rescore sampling, drift
detection, and the per-process half of the per-generation scorecards.

Every quality number the system had before this module was offline —
bench stages and the nightly gates measure synthetic corpora, while the
traffic actually being served was quality-blind
(``lsh_measured_recall_at_10`` proved the assumed-0.95 LSH recall was
really 0.49, and only because bench sampled its own responses). This
module measures the model being served, on the traffic it serves:

- **Shadow rescore sampling**: a config-gated fraction
  (``oryx.monitoring.quality.sample-rate``) of served top-k responses is
  re-scored EXACTLY off the hot path — the request thread only flips a
  coin and enqueues a reference into a bounded queue; a dedicated drain
  thread replays each sample through the serve path's exact host kernel
  (``serving/batcher.host_topk``, the same code the device-down fallback
  serves with) and compares. Overflow drops the sample, never the
  request (``oryx_quality_sample_drops_total``). Results export as the
  windowed ``oryx_live_recall_at_k{score_mode}`` gauge plus the
  ``oryx_live_score_margin`` histogram (relative score given up by the
  approximation, trace exemplars attached) — quantized/approx/LSH recall
  becomes a runtime fact instead of a bench claim.

- **Input & prediction drift**: batch generations persist a compact
  ``TrainingProfile`` (item-popularity sketch, event rate, new-item
  fraction, score distribution) inside the model artifact; the serving
  and speed tiers compare live windowed sketches against the served
  generation's profile into ``oryx_input_drift{signal}`` /
  ``oryx_prediction_drift{signal}`` gauges. The speed tier sees the raw
  event stream (input drift); serving sees its own served scores through
  the sampler (prediction drift).

- **Quality SLO + alarms**: each shadow sample is good/bad against
  ``oryx.monitoring.slo.quality.recall-floor``; the cumulative counters
  feed the ``quality`` SLO burn rate (``common/slo.py``). When the fast
  burn crosses ``oryx.monitoring.quality.alarm-burn-rate`` while the
  live window sits below the floor, a ``quality-alarm`` flight event is
  recorded with the serving generation id — the exact signal a canary
  gate consumes. Drift past ``oryx.monitoring.quality.drift.alarm-
  threshold`` records a ``drift-alarm`` event the same way.

The sampler's cost model: one exact rescore is an O(N·F) host matmul —
at 1M×50f that is ~200 MB of reads per sample, so the budget lives in
``sample-rate`` (default 1%) and the bounded queue, never in request
latency. ``tools/chaos.py degraded-model`` proves the whole loop end to
end, including that a saturated shadow queue drops samples instead of
slowing requests.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import queue
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from oryx_tpu.common.metrics import get_registry, linear_buckets

log = logging.getLogger(__name__)

# Item-popularity sketch width: 64 hash buckets is enough to see a
# category-level shift (total-variation distance) at ~8 bytes/bucket in
# the artifact, and small enough that the per-event hashing cost is the
# blake2b call, not cache misses.
SKETCH_BUCKETS = 64

# Live windows are deques of (monotonic-time, payload); bounded so a
# misconfigured window-sec cannot grow them without limit.
_MAX_WINDOW_SAMPLES = 4096

# The recall the live gauge reports at: recall@min(10, served page).
LIVE_RECALL_K = 10

# Relative score margin histogram: 0 (approximation gave up nothing)
# through 0.24+ in 0.01 steps — linear because the interesting end is 0.
MARGIN_BUCKETS = linear_buckets(0.0, 0.01, 25)


def sketch_bucket(item_id: str) -> int:
    """Stable hash bucket of an item id (blake2b, process-independent —
    the profile is computed in the batch process and compared in
    serving/speed processes, so the builtin salted hash() would never
    match)."""
    h = hashlib.blake2b(item_id.encode("utf-8"), digest_size=4).digest()
    return int.from_bytes(h, "big") % SKETCH_BUCKETS


def sketch_of(item_ids, weights=None) -> np.ndarray:
    """Normalized popularity sketch (sums to 1.0; zeros when empty)."""
    counts = np.zeros(SKETCH_BUCKETS, dtype=np.float64)
    for j, ident in enumerate(item_ids):
        w = 1.0 if weights is None else float(weights[j])
        counts[sketch_bucket(str(ident))] += abs(w)
    total = counts.sum()
    return counts / total if total > 0 else counts


@dataclass
class TrainingProfile:
    """Compact what-the-model-was-trained-on summary, persisted as the
    ``qualityProfile`` model-artifact extension. All fields are
    denominator-safe: a missing signal is None, never a guessed 0."""

    item_sketch: list[float] = field(default_factory=list)
    events_per_sec: float | None = None
    new_item_fraction: float | None = None
    score_mean: float | None = None
    score_std: float | None = None
    n_events: int = 0

    def to_json(self) -> str:
        return json.dumps({
            "item_sketch": [round(float(v), 6) for v in self.item_sketch],
            "events_per_sec": self.events_per_sec,
            "new_item_fraction": self.new_item_fraction,
            "score_mean": self.score_mean,
            "score_std": self.score_std,
            "n_events": int(self.n_events),
        })

    @staticmethod
    def from_json(text: str) -> "TrainingProfile":
        doc = json.loads(text)
        if not isinstance(doc, dict):
            raise ValueError("qualityProfile is not a JSON object")

        def num(k):
            v = doc.get(k)
            return float(v) if isinstance(v, (int, float)) else None

        sketch = doc.get("item_sketch") or []
        return TrainingProfile(
            item_sketch=[float(v) for v in sketch],
            events_per_sec=num("events_per_sec"),
            new_item_fraction=num("new_item_fraction"),
            score_mean=num("score_mean"),
            score_std=num("score_std"),
            n_events=int(doc.get("n_events") or 0),
        )


def build_training_profile(
    item_ids,
    item_counts=None,
    *,
    timestamps_ms=None,
    prev_item_ids=None,
    scores=None,
) -> TrainingProfile:
    """Build the profile a batch generation stamps into its artifact.

    ``item_ids`` + optional per-id ``item_counts`` describe the window's
    item-interaction distribution (aggregated pairs are fine — the
    sketch is a popularity shape, not an exact count). ``timestamps_ms``
    (raw window event timestamps) give the event rate;
    ``prev_item_ids`` (previous generation's catalog) gives the new-item
    fraction; ``scores`` is a sample of the trained model's predicted
    scores for the prediction-drift baseline."""
    sketch = sketch_of(item_ids, item_counts)
    rate = None
    n_events = len(item_ids)
    if timestamps_ms is not None and len(timestamps_ms) > 1:
        ts = np.asarray(timestamps_ms, dtype=np.int64)
        ts = ts[ts > 0]
        if ts.size > 1:
            span_s = float(ts.max() - ts.min()) / 1000.0
            n_events = int(ts.size)
            if span_s > 0:
                rate = n_events / span_s
    new_frac = None
    if prev_item_ids is not None:
        prev = set(prev_item_ids)
        if prev:
            ids = list(item_ids)
            if ids:
                new_frac = sum(1 for i in ids if str(i) not in prev) / len(ids)
        else:
            new_frac = 0.0
    s_mean = s_std = None
    if scores is not None and len(scores) > 0:
        s = np.asarray(scores, dtype=np.float64)
        s = s[np.isfinite(s)]
        if s.size:
            s_mean = float(s.mean())
            s_std = float(s.std())
    return TrainingProfile(
        item_sketch=[float(v) for v in sketch],
        events_per_sec=rate,
        new_item_fraction=new_frac,
        score_mean=s_mean,
        score_std=s_std,
        n_events=n_events,
    )


@dataclass
class _Sample:
    """One shadow-rescore work item: everything the drain thread needs
    to replay the request exactly, by reference (the snapshot_fn hands
    back the row-aligned host matrix + ids the request was served from,
    or takes a fresh store snapshot for the LSH host path)."""

    vec: np.ndarray
    served_ids: list
    served_scores: list
    how_many: int
    exclude: frozenset
    cosine: bool
    score_mode: str
    trace_id: str | None
    snapshot_fn: object  # () -> (host f32 matrix, ids, valid_rows)


_INPUT_SIGNALS = ("item-popularity", "event-rate", "new-item-fraction")
_PREDICTION_SIGNALS = ("score-mean", "score-spread")


class QualityStats:
    """Process-global live-quality tracker (``get_qualitystats()``); a
    fresh instance is also constructable for tests and the nightly
    harness."""

    def __init__(self):
        self.enabled = True
        self.sample_rate = 0.0
        self.window_s = 300.0
        self.max_queue = 256
        self.recall_floor = 0.9
        self.alarm_burn_rate = 14.0
        self.drift_alarm = 0.5
        self._lock = threading.Lock()
        # unbounded Queue with the bound enforced at enqueue (qsize
        # probe): reconfiguring max-queue then never orphans in-flight
        # samples in a replaced queue object
        self._queue: queue.Queue[_Sample] = queue.Queue()
        self._stop = threading.Event()
        # writes serialized under _lock; the pre-lock aliveness probe in
        # _ensure_thread is a deliberate lock-free fast path
        self._thread: threading.Thread | None = None  # guarded-by: _lock (writes)
        self._enqueued = 0  # guarded-by: _lock (accepted-sample count)
        self._processed = 0  # guarded-by: _lock (drained-sample count)
        # (t, recall, margin) per score_mode label
        self._recall_window: dict[str, deque] = {}  # guarded-by: _lock
        # live prediction-score window: (t, mean-served-score)
        self._score_window: deque = deque(maxlen=_MAX_WINDOW_SAMPLES)  # guarded-by: _lock
        # live input window: (t, bucket-count sketch, n_events, n_new)
        self._input_window: deque = deque(maxlen=_MAX_WINDOW_SAMPLES)  # guarded-by: _lock
        self._known_items: set[str] = set()  # guarded-by: _lock (new-item tracking)
        self.profile: TrainingProfile | None = None
        # test/chaos hook: while set, the drain thread parks — the only
        # way to deterministically exercise the overflow-drop contract
        # (the real drain races ahead of any realistic request burst)
        self.drain_gate = threading.Event()
        self._metrics = None

    # -- configuration -----------------------------------------------------

    def configure(self, config) -> None:
        """Adopt the oryx.monitoring.quality.* keys and pre-register the
        metric families (zero baselines from process start, like the
        robustness metrics)."""
        self.enabled = config.get_bool("oryx.monitoring.quality.enabled", True)
        self.sample_rate = max(
            0.0, config.get_float("oryx.monitoring.quality.sample-rate", 0.01)
        )
        self.window_s = max(
            1.0, config.get_float("oryx.monitoring.quality.window-sec", 300.0)
        )
        self.max_queue = max(
            1, config.get_int("oryx.monitoring.quality.max-queue", 256)
        )
        self.recall_floor = config.get_float(
            "oryx.monitoring.slo.quality.recall-floor", 0.9
        )
        self.alarm_burn_rate = config.get_float(
            "oryx.monitoring.quality.alarm-burn-rate", 14.0
        )
        self.drift_alarm = config.get_float(
            "oryx.monitoring.quality.drift.alarm-threshold", 0.5
        )
        self.ensure_metrics()
        # the quality SLO burns over this sampler's good/bad counters
        from oryx_tpu.common import slo

        if self.enabled and self.sample_rate > 0:
            slo.ensure_quality_slo(config)

    def ensure_metrics(self) -> None:
        """Register the live-quality families (idempotent)."""
        if self._metrics is not None:
            return
        reg = get_registry()
        g_recall = reg.gauge(
            "oryx_live_recall_at_k",
            "Windowed mean recall@k of shadow-rescored served responses "
            "against the exact host rescore, by serving score mode "
            "(NaN until a sample lands in the window) — the runtime "
            "counterpart of bench's measured-recall fields",
            labeled=True,
        )
        h_margin = reg.histogram(
            "oryx_live_score_margin",
            "Relative score the serving approximation gave up per shadow "
            "sample: (exact top-score - served top-score) / |exact "
            "top-score| (0 = the approximation found the true winner); "
            "buckets carry trace exemplars while tracing is on",
            buckets=MARGIN_BUCKETS,
        )
        c_samples = reg.counter(
            "oryx_quality_samples_total",
            "Served responses shadow-rescored by the live quality "
            "sampler, by serving score mode",
            labeled=True,
        )
        c_bad = reg.counter(
            "oryx_quality_bad_samples_total",
            "Shadow samples whose measured recall fell below "
            "oryx.monitoring.slo.quality.recall-floor — the bad half of "
            "the quality SLO's burn-rate fraction",
            labeled=True,
        )
        c_drops = reg.counter(
            "oryx_quality_sample_drops_total",
            "Shadow samples dropped because the bounded rescore queue "
            "was full — the request was served normally; only the "
            "quality measurement was skipped",
        )
        g_in = reg.gauge(
            "oryx_input_drift",
            "Live input stream vs the served generation's training "
            "profile, by signal: item-popularity (total-variation "
            "distance of hash sketches, 0..1), event-rate (relative "
            "change), new-item-fraction (absolute fraction of events on "
            "items the generation never trained on). NaN until both a "
            "profile and a live window exist",
            labeled=True,
        )
        g_pred = reg.gauge(
            "oryx_prediction_drift",
            "Live served-score distribution vs the served generation's "
            "training profile, by signal: score-mean (relative shift), "
            "score-spread (relative std change). NaN until both a "
            "profile and sampled predictions exist",
            labeled=True,
        )
        for signal in _INPUT_SIGNALS:
            g_in.set_function(
                self._drift_reader(self.input_drift, signal), signal=signal
            )
        for signal in _PREDICTION_SIGNALS:
            g_pred.set_function(
                self._drift_reader(self.prediction_drift, signal),
                signal=signal,
            )
        self._metrics = (g_recall, h_margin, c_samples, c_bad, c_drops)

    @staticmethod
    def _drift_reader(fn, signal: str):
        return lambda: fn(signal)

    # -- shadow sampling (request side) ------------------------------------

    def maybe_sample(
        self,
        vec,
        served_pairs,
        *,
        how_many: int,
        exclude=frozenset(),
        cosine: bool = False,
        score_mode: str = "exact",
        trace_id: str | None = None,
        snapshot_fn=None,
    ) -> bool:
        """Request-side hook, called AFTER the response is final (post
        pool / host-path caller thread, never the batcher dispatcher).
        The hot-path cost is one RNG draw and a put_nowait; everything
        else happens on the drain thread. Returns True when enqueued."""
        if not self.enabled or self.sample_rate <= 0 or snapshot_fn is None:
            return False
        if not served_pairs:
            return False
        if self.sample_rate < 1.0 and random.random() >= self.sample_rate:
            return False
        sample = _Sample(
            vec=np.array(vec, dtype=np.float32, copy=True),
            served_ids=[p[0] for p in served_pairs],
            served_scores=[float(p[1]) for p in served_pairs],
            how_many=int(how_many),
            exclude=frozenset(exclude),
            cosine=bool(cosine),
            score_mode=str(score_mode),
            trace_id=trace_id,
            snapshot_fn=snapshot_fn,
        )
        if self._queue.qsize() >= self.max_queue:
            # overflow drops the SAMPLE, never the request: the queue
            # bound is the proof sampling stays off the dispatch path
            self.ensure_metrics()
            self._metrics[4].inc()
            return False
        self._queue.put_nowait(sample)
        with self._lock:
            self._enqueued += 1
        self._ensure_thread()
        return True

    def _ensure_thread(self) -> None:
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._lock:
            t = self._thread
            if t is None or not t.is_alive():
                t = threading.Thread(
                    target=self._drain_loop,
                    name="oryx-quality-sampler",
                    daemon=True,
                )
                self._thread = t
                t.start()

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait until every accepted sample has been fully processed
        (tests, chaos, bench — never the request path). Dropped samples
        never count as accepted, so a paused drain + overflow still
        flushes once unblocked."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                done = self._processed >= self._enqueued
            if done and not self.drain_gate.is_set():
                return True
            time.sleep(0.01)
        return False

    # -- shadow sampling (drain side) --------------------------------------

    def close(self) -> None:
        """Stop the drain thread (private instances in tests/harnesses;
        the process singleton just lives as long as the process)."""
        self._stop.set()

    def _drain_loop(self) -> None:  # oryxlint: offloop (dedicated shadow-rescore thread)
        while not self._stop.is_set():
            try:
                sample = self._queue.get(timeout=1.0)
            except queue.Empty:
                continue
            while self.drain_gate.is_set() and not self._stop.is_set():
                time.sleep(0.005)
            try:
                self._process(sample)
            except Exception:  # noqa: BLE001 - the sampler never breaks serving
                log.exception("shadow rescore sample failed")
            finally:
                with self._lock:
                    self._processed += 1

    def _process(self, sample: _Sample) -> None:
        recall, margin = self.score_sample(sample)
        if recall is None:
            return
        self.ensure_metrics()
        g_recall, h_margin, c_samples, c_bad, _ = self._metrics
        c_samples.inc(score_mode=sample.score_mode)
        bad = recall < self.recall_floor
        if bad:
            c_bad.inc(score_mode=sample.score_mode)
        h_margin.observe(margin, trace_id=sample.trace_id)
        now = time.monotonic()
        with self._lock:
            win = self._recall_window.setdefault(
                sample.score_mode, deque(maxlen=_MAX_WINDOW_SAMPLES)
            )
            win.append((now, recall))
            if sample.served_scores:
                self._score_window.append(
                    (now, float(np.mean(sample.served_scores)))
                )
        # gauge callbacks are bound per observed score mode (labels are
        # data-driven; binding is idempotent per mode)
        g_recall.set_function(
            self._recall_reader(sample.score_mode),
            score_mode=sample.score_mode,
        )
        self.evaluate_alarms()
        # prediction drift only fills HERE (a serving-only process never
        # sees note_input_events), so its alarm must also fire here
        self.evaluate_drift_alarms()

    def score_sample(self, sample: _Sample):
        """The one shadow-rescore computation (shared with the nightly
        harness): exact host top-k over the full matrix, same exclusion
        trim as serving, recall@min(k, served page) + relative score
        margin. Returns (None, None) when the sample can't be scored."""
        from oryx_tpu.serving.batcher import host_topk

        try:
            mat, ids, n = sample.snapshot_fn()
        except Exception:  # noqa: BLE001 - a racing model swap loses one sample
            return None, None
        if mat is None or n == 0:
            return None, None
        mat = np.asarray(mat, dtype=np.float32)[:n]
        k_fetch = min(n, sample.how_many + len(sample.exclude) + 8)
        vals, idx = host_topk(sample.vec, k_fetch, mat, sample.cosine)
        exact_pairs = []
        for v, j in zip(np.asarray(vals), np.asarray(idx)):
            ident = ids[int(j)]
            if ident in sample.exclude:
                continue
            exact_pairs.append((ident, float(v)))
            if len(exact_pairs) == sample.how_many:
                break
        if not exact_pairs:
            return None, None
        k = min(LIVE_RECALL_K, sample.how_many,
                len(sample.served_ids), len(exact_pairs))
        if k == 0:
            return None, None
        exact_ids = [i for i, _ in exact_pairs[:k]]
        recall = len(set(sample.served_ids[:k]) & set(exact_ids)) / k
        exact_top = exact_pairs[0][1]
        served_top = sample.served_scores[0] if sample.served_scores else 0.0
        denom = max(abs(exact_top), 1e-9)
        margin = max(0.0, (exact_top - served_top) / denom)
        return float(recall), float(margin)

    def _recall_reader(self, score_mode: str):
        return lambda: self.live_recall(score_mode)

    def _window_bad_fraction(self) -> float | None:
        """Fraction of window samples below the recall floor (None on an
        empty window) — the alarm path's fresh numerator; the SLO ring
        stores at most one sample per 50ms and a fast drain can outrun
        it."""
        cutoff = time.monotonic() - self.window_s
        bad = total = 0
        with self._lock:
            for win in self._recall_window.values():
                for t, r in win:
                    if t >= cutoff:
                        total += 1
                        if r < self.recall_floor:
                            bad += 1
        return (bad / total) if total else None

    def live_recall(self, score_mode: str | None = None) -> float:
        """Windowed mean recall (NaN when no sample landed in the
        window). score_mode=None pools every mode — the console/healthz
        headline."""
        cutoff = time.monotonic() - self.window_s
        vals: list[float] = []
        with self._lock:
            wins = (
                list(self._recall_window.values())
                if score_mode is None
                else [self._recall_window.get(score_mode, deque())]
            )
            for win in wins:
                vals.extend(r for t, r in win if t >= cutoff)
        return float(np.mean(vals)) if vals else float("nan")

    def samples_processed(self) -> int:
        with self._lock:
            return self._processed

    # -- drift -------------------------------------------------------------

    def note_generation(self, generation=None) -> None:
        """A new model generation was adopted for serving (freshness
        tracker hook): the recall/served-score windows describe the
        PREVIOUS generation's answers, and pooling them forward would
        let a quality-alarm fire under a healthy rollback generation
        while naming IT as degraded — the windows are generation-scoped,
        the cumulative SLO counters deliberately are not."""
        with self._lock:
            self._recall_window.clear()
            self._score_window.clear()

    def set_training_profile(self, profile: TrainingProfile) -> None:
        """Adopt the served generation's profile (called when a MODEL
        artifact carrying a qualityProfile extension loads). The live
        new-item tracking resets to the generation's catalog view."""
        self.profile = profile
        with self._lock:
            self._input_window.clear()

    def note_catalog(self, item_ids) -> None:
        """Items the served generation knows — the denominator of the
        live new-item fraction."""
        with self._lock:
            self._known_items = set(str(i) for i in item_ids)

    def note_input_events(self, item_ids, timestamps_ms=None) -> None:
        """Speed/serving-side hook: fold one micro-batch of raw input
        events into the live input window. Cost is one blake2b per event
        — micro-batch granularity, never per-request."""
        if not self.enabled:
            return
        ids = [str(i) for i in item_ids]
        if not ids:
            return
        counts = np.zeros(SKETCH_BUCKETS, dtype=np.float64)
        for ident in ids:
            counts[sketch_bucket(ident)] += 1.0
        with self._lock:
            known = self._known_items
            n_new = sum(1 for i in ids if i not in known) if known else 0
            self._input_window.append(
                (time.monotonic(), counts, len(ids), n_new)
            )
        self.evaluate_drift_alarms()

    def _live_input(self):
        """(pooled sketch counts, events, new) inside the window."""
        cutoff = time.monotonic() - self.window_s
        counts = np.zeros(SKETCH_BUCKETS, dtype=np.float64)
        n_events = n_new = 0
        oldest = None
        with self._lock:
            for t, c, n, new in self._input_window:
                if t < cutoff:
                    continue
                counts += c
                n_events += n
                n_new += new
                oldest = t if oldest is None else min(oldest, t)
        span = (time.monotonic() - oldest) if oldest is not None else 0.0
        return counts, n_events, n_new, span

    def input_drift(self, signal: str) -> float:
        """Live-vs-profile distance for one input signal; NaN without
        both sides."""
        p = self.profile
        if p is None:
            return float("nan")
        counts, n_events, n_new, span = self._live_input()
        if n_events == 0:
            return float("nan")
        if signal == "item-popularity":
            if not p.item_sketch:
                return float("nan")
            live = counts / counts.sum()
            prof = np.asarray(p.item_sketch, dtype=np.float64)
            if prof.sum() <= 0:
                return float("nan")
            # total-variation distance: 0 = identical shape, 1 = disjoint
            return float(0.5 * np.abs(live - prof / prof.sum()).sum())
        if signal == "event-rate":
            if p.events_per_sec is None or p.events_per_sec <= 0 or span <= 0:
                return float("nan")
            live_rate = n_events / span
            return float(
                abs(live_rate - p.events_per_sec) / p.events_per_sec
            )
        if signal == "new-item-fraction":
            with self._lock:
                if not self._known_items:
                    return float("nan")
            return float(n_new / n_events)
        return float("nan")

    def prediction_drift(self, signal: str) -> float:
        p = self.profile
        if p is None:
            return float("nan")
        cutoff = time.monotonic() - self.window_s
        with self._lock:
            scores = [s for t, s in self._score_window if t >= cutoff]
        if not scores:
            return float("nan")
        live_mean = float(np.mean(scores))
        live_std = float(np.std(scores))
        if signal == "score-mean":
            if p.score_mean is None:
                return float("nan")
            denom = max(abs(p.score_mean), p.score_std or 0.0, 1e-9)
            return abs(live_mean - p.score_mean) / denom
        if signal == "score-spread":
            if p.score_std is None or p.score_std <= 0:
                return float("nan")
            return abs(live_std - p.score_std) / p.score_std
        return float("nan")

    # -- alarms ------------------------------------------------------------

    def evaluate_alarms(self) -> bool:
        """Fire a ``quality-alarm`` flight event when the quality SLO's
        fast burn rate crosses the alarm threshold while the live recall
        window sits below the floor — the burn-rate/flight machinery a
        degraded generation must trip. Called per drained sample (and by
        tests); rate-limited by the flight recorder's episode window."""
        from oryx_tpu.common import slo

        t = slo.tracker("quality")
        if t is None:
            return False
        burn = t.burn_rate(t.fast_s)
        # the scrape-driven ring is bounded to one sample per 50ms, so a
        # burst the drain scores faster than that can sit between ring
        # samples; derive the burn from the sampler's own window too
        # (identical objective/budget semantics, fresher numerator) and
        # alarm on the larger
        budget = 1.0 - t.objective
        frac = self._window_bad_fraction()
        if budget > 0 and frac is not None:
            burn = max(burn, frac / budget)
        recall = self.live_recall()
        if burn < self.alarm_burn_rate or math.isnan(recall):
            return False
        if recall >= self.recall_floor:
            return False
        from oryx_tpu.common.flightrec import get_flightrec
        from oryx_tpu.common.freshness import model_freshness

        return get_flightrec().record(
            kind="quality-alarm",
            episode_s=30.0,
            generation=model_freshness().generation,
            live_recall=round(recall, 4),
            recall_floor=self.recall_floor,
            burn_rate=round(burn, 2),
        )

    def evaluate_drift_alarms(self) -> bool:
        """Fire a ``drift-alarm`` flight event when any drift signal
        crosses the configured threshold (episode-limited)."""
        worst_signal, worst = None, 0.0
        for signal in _INPUT_SIGNALS:
            v = self.input_drift(signal)
            if not math.isnan(v) and v > worst:
                worst_signal, worst = f"input:{signal}", v
        for signal in _PREDICTION_SIGNALS:
            v = self.prediction_drift(signal)
            if not math.isnan(v) and v > worst:
                worst_signal, worst = f"prediction:{signal}", v
        if worst_signal is None or worst < self.drift_alarm:
            return False
        from oryx_tpu.common.flightrec import get_flightrec
        from oryx_tpu.common.freshness import model_freshness

        return get_flightrec().record(
            kind="drift-alarm",
            episode_s=30.0,
            generation=model_freshness().generation,
            signal=worst_signal,
            value=round(worst, 4),
            threshold=self.drift_alarm,
        )

    # -- surfaces ----------------------------------------------------------

    def healthz_section(self) -> dict:
        """The /healthz ``quality`` body section (and, probed from it,
        each replica's scorecard in /fleet/status). Cheap enough for the
        nonblocking healthz handler: window reads under one lock, all
        values JSON-finite."""
        from oryx_tpu.common.freshness import model_freshness

        def fin(v):
            return (
                round(v, 4)
                if isinstance(v, (int, float)) and math.isfinite(v)
                else None
            )

        self.ensure_metrics()
        out: dict = {
            "live_recall_at_10": fin(self.live_recall()),
            "samples": self.samples_processed(),
            "dropped": int(self._metrics[4].value()),
            "sample_rate": self.sample_rate,
        }
        mf = model_freshness()
        if getattr(mf, "quality", None):
            out["generation_quality"] = {
                str(k): fin(v) for k, v in mf.quality.items()
            }
        drift_in = {
            s: fin(self.input_drift(s)) for s in _INPUT_SIGNALS
        }
        drift_pred = {
            s: fin(self.prediction_drift(s)) for s in _PREDICTION_SIGNALS
        }
        if any(v is not None for v in drift_in.values()):
            out["input_drift"] = drift_in
        if any(v is not None for v in drift_pred.values()):
            out["prediction_drift"] = drift_pred
        return out


# -- process-global instance --------------------------------------------------

_default = QualityStats()


def get_qualitystats() -> QualityStats:
    return _default


def configure_qualitystats(config) -> QualityStats:
    _default.configure(config)
    return _default
