"""Mini resource framework: routing, content negotiation, readiness gating.

Plays the role of Jersey + the serving base resources
(OryxApplication.java's annotation scan, AbstractOryxResource's model
readiness gate and sendInput, CSVMessageBodyWriter's text/csv rendering,
OryxExceptionMapper's error mapping — SURVEY.md §2.5, §2.11). Routes are
registered by app modules through register(app); path patterns support
{name} segments and {name:rest} tails.
"""

from __future__ import annotations

import json
import re
import threading
import time
import weakref
from concurrent.futures import Future

from oryx_tpu.serving.futureutil import try_set_exception, try_set_result
from dataclasses import dataclass, field
from typing import Any, Callable

from oryx_tpu.api import ServingModelManager
from oryx_tpu.bus.api import TopicProducer
from oryx_tpu.common.config import Config
from oryx_tpu.common.metrics import GaugeSeriesGone, get_registry
from oryx_tpu.common.perfattr import swap_ledger
from oryx_tpu.common.tracing import configure_tracing, swap_current


@dataclass
class RawResponse:
    """Bypass content negotiation — body served verbatim (e.g. /metrics
    Prometheus text, HTML consoles)."""

    status: int
    body: bytes
    content_type: str


@dataclass
class Deferred:
    """A handler result that completes later (device-batched endpoints).

    Handlers return Deferred(future-of-raw-result) instead of parking
    their worker thread on the micro-batcher; the async frontend awaits
    the future on the event loop, so in-flight request capacity is bounded
    by memory, not by worker-pool threads (the reference's analogue is
    Tomcat NIO async servlets). The threaded frontend and direct
    dispatch() callers keep blocking semantics.
    """

    future: "Future"


def chain_future(
    future: "Future", fn: Callable[[Any], Any], executor=None
) -> "Future":
    """Future of fn(future.result()), exceptions carried through. With an
    executor, fn runs there instead of inline in the completing thread —
    REQUIRED when the completing thread is a latency-critical loop (the
    batcher dispatcher) or when fn may block."""
    out: Future = Future()

    def _apply(f):
        # out may already be cancelled: the async frontend's
        # asyncio.wrap_future cancels it on client disconnect / shutdown
        # drain — try_set absorbs the lost race instead of raising
        # InvalidStateError inside a done-callback
        try:
            result = fn(f.result())
        except BaseException as e:  # noqa: BLE001 - carried downstream
            try_set_exception(out, e)
            return
        try_set_result(out, result)

    if executor is None:
        future.add_done_callback(_apply)
    else:

        def _bounce(f):
            try:
                executor.submit(_apply, f)
            except Exception:
                # pool shut down: fail the future rather than leave
                # blocked callers hanging — and never run fn inline here,
                # because the completing thread may be the batcher
                # dispatcher, which arbitrary fn code could deadlock
                try_set_exception(
                    out, RuntimeError("post-processing pool is shut down")
                )
        future.add_done_callback(_bounce)
    return out


def deferred_map(future: "Future", fn: Callable[[Any], Any]) -> Deferred:
    """Deferred whose result is fn(future.result())."""
    return Deferred(chain_future(future, fn))


_POST_POOL = None
_POST_POOL_LOCK = threading.Lock()
_POST_POOL_WORKERS = 8  # overridden from config by the serving managers


def configure_post_pool(workers: int) -> None:
    """Size the post-processing pool (oryx.serving.api.post-workers) —
    takes effect at first use; an already-created pool keeps its size."""
    global _POST_POOL_WORKERS
    _POST_POOL_WORKERS = max(1, int(workers))


def post_pool():
    """Shared pool for per-request post-processing chained off batcher
    futures (sized for trim/render work; a rescorer that blocks holds one
    of these threads, never the batcher dispatcher — and blocking top_n()
    callers post-process on their own thread, so nested rescorer queries
    cannot exhaust this pool into a deadlock). Shared across apps: the
    ALS recommend family and the seq /recommend-next chain through it."""
    global _POST_POOL
    if _POST_POOL is None:
        with _POST_POOL_LOCK:
            if _POST_POOL is None:
                from concurrent.futures import ThreadPoolExecutor

                _POST_POOL = ThreadPoolExecutor(
                    max_workers=_POST_POOL_WORKERS,
                    thread_name_prefix="oryx-topn-post",
                )
    return _POST_POOL


class OryxServingException(Exception):
    """HTTP-status-carrying error (reference OryxServingException).
    ``headers`` ride the response (e.g. Retry-After on a load shed)."""

    def __init__(
        self,
        status: int,
        message: str = "",
        headers: tuple[tuple[str, str], ...] = (),
    ):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = headers


class ShedLoad(OryxServingException):
    """Deliberate 503 under saturation: the serving tier refuses work it
    cannot queue honestly (batcher backlog past its bound) instead of
    letting latency grow without limit. Carries Retry-After so
    well-behaved clients back off. The shed DECISION site (not this
    constructor) increments `oryx_serving_shed_total`, so the chaos
    suite can tell a deliberate shed from a real 5xx without merely-
    constructed instances skewing the count."""

    def __init__(self, message: str = "overloaded", retry_after_sec: int = 1):
        super().__init__(
            503, message,
            headers=(("Retry-After", str(int(retry_after_sec))),),
        )


@dataclass
class Request:
    method: str
    path: str
    params: dict[str, str]
    query: dict[str, list[str]]
    body: bytes
    headers: dict[str, str]
    # the request's tracing span (common/tracing.py), set by the frontend
    # when tracing is enabled; dispatch installs it as the thread-current
    # span so batcher/bus instrumentation parents to it
    trace: Any = None
    # the request's phase ledger (common/perfattr.py PhaseLedger), created
    # by the frontend at parse time and flushed by it after the response
    # bytes are written; dispatch installs it as the thread-current ledger
    # so the batcher stamps queue/pad/device phases without signature
    # threading. None when dispatched outside an HTTP frontend.
    ledger: Any = None
    # extra RESPONSE headers accumulated during dispatch (Retry-After on
    # sheds, Warning on stale-model responses); frontends read this after
    # the response renders. A side channel rather than a wider render
    # tuple so the (status, body, content_type) contract stays stable.
    response_headers: list = field(default_factory=list)

    def q1(self, name: str, default: str | None = None) -> str | None:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def q_list(self, name: str) -> list[str]:
        return self.query.get(name, [])

    def body_text(self) -> str:
        return self.body.decode("utf-8")


@dataclass
class _Route:
    method: str
    pattern: re.Pattern
    handler: Callable[["ServingApp", Request], Any]
    nonblocking: bool = False


def _compile(pattern: str) -> re.Pattern:
    parts = []
    for seg in pattern.strip("/").split("/"):
        if seg.startswith("{") and seg.endswith("}"):
            name = seg[1:-1]
            if name.endswith(":rest"):
                parts.append(f"(?P<{name[:-5]}>.+)")
            else:
                parts.append(f"(?P<{name}>[^/]+)")
        else:
            parts.append(re.escape(seg))
    return re.compile("^/" + "/".join(parts) + "$")


def _first_literal(pattern: str) -> str | None:
    """The pattern's literal first segment, or None when it's a parameter —
    the index key for O(1) route-group lookup on the hot path."""
    seg = pattern.strip("/").split("/", 1)[0]
    return None if seg.startswith("{") else seg


class ServingApp:
    """Holds the model manager, input producer, config, and route table."""

    def __init__(
        self,
        config: Config,
        model_manager: ServingModelManager,
        input_producer: TopicProducer | None = None,
    ):
        self.config = config
        self.model_manager = model_manager
        self.input_producer = input_producer
        self.min_fraction = config.get_float("oryx.serving.min-model-load-fraction", 0.8)
        # degraded-mode bound: a served model whose publish stamp is older
        # than this gets a Warning: 110 header on every model-backed
        # response and flips /healthz readiness (null = no bound). The
        # model still serves — stale answers beat no answers — but probes
        # and clients can SEE the degradation.
        raw_stale = config.get("oryx.serving.api.max-staleness-sec", None)
        self.max_staleness_sec = float(raw_stale) if raw_stale is not None else None
        # fleet identity: names this process in degraded reasons, the
        # fleet front's ejection log, and oryx_fleet_replica_* labels
        # (set per replica by fleet/supervisor.py; null outside a fleet)
        self.replica_id = config.get_string("oryx.fleet.replica.id", None)
        # the bound listening port, filled in by the serving layer once
        # the (possibly ephemeral) bind resolves; 0 until then
        self.listen_port = 0
        # update-topic consumer backlog callback (ConsumeDataIterator.lag),
        # wired by ServingLayer so /healthz can report update_lag
        self.update_lag_fn = None
        # mount point (reference: Tomcat context path, ServingLayer.java);
        # "" = root. Requests outside the prefix 404 before routing.
        raw_ctx = (config.get_string("oryx.serving.api.context-path", "/") or "/").strip("/")
        self.context_path = f"/{raw_ctx}" if raw_ctx else ""
        self.routes: list[_Route] = []
        # routes indexed by literal first path segment; None key holds
        # patterns whose first segment is a parameter (scanned after the
        # group). Dispatch touches ~2 candidate routes instead of all.
        self._route_index: dict[str | None, list[_Route]] = {}
        # fully-literal patterns resolved by ONE dict lookup on
        # (method, path) — no regex on the hot path. Consistent with the
        # precedence contract: an exact hit IS the winning literal route
        # (first registration wins via setdefault; a miss — unknown path
        # or method — falls through to the indexed scan for 404/405).
        self._exact_routes: dict[tuple[str, str], _Route] = {}
        self.fast_segments: set[str] = set()
        self._slow_segments: set[str] = set()
        self._wildcard_blocking = False
        # app modules append (title, fn(app) -> rows) callbacks here; the
        # generic /console renders each as its own table — the equivalent
        # of the reference's per-app Console subclasses (e.g. als/Console.java)
        self.console_sections: list[tuple[str, Callable[["ServingApp"], list[tuple[str, Any]]]]] = []
        # tracing follows THIS app's config (last constructed wins — one
        # config per process); /healthz reports uptime + frontend fan-out
        configure_tracing(config)
        # runtime perf accounting (live MFU/occupancy gauges, /debug/
        # profile window knobs) adopts the same config and pre-registers
        # its metric families
        from oryx_tpu.common.perfstats import configure_perfstats

        configure_perfstats(config)
        # latency attribution (phase budgets, idle-gap classification,
        # compile-storm + burn-triggered capture knobs) adopts the same
        # config and pre-registers its families
        from oryx_tpu.common.perfattr import configure_perfattr

        configure_perfattr(config)
        # the update-topic listener's artifact relay adopts the fleet's
        # distribution mode (shared per-host cache vs per-process decode)
        from oryx_tpu.common.artifact import configure_artifact_relay

        configure_artifact_relay(config)
        # the flight recorder (on-disk lifecycle ring + snapshot bundler,
        # common/flightrec.py) and the config-declared SLO burn-rate
        # gauges (common/slo.py) adopt the same config
        from oryx_tpu.common.flightrec import configure_flightrec
        from oryx_tpu.common.slo import ensure_serving_slos

        configure_flightrec(config).record(
            kind="process-start",
            role="serving",
            port=config.get_int("oryx.serving.api.port", 0),
        )
        ensure_serving_slos(config)
        # live model-quality plane (common/qualitystats.py): shadow
        # rescore sampling of served responses, drift gauges, and the
        # quality SLO — adopt the same config and pre-register families
        from oryx_tpu.common.qualitystats import configure_qualitystats

        configure_qualitystats(config)
        # staged model adoption (common/modelgate.py): canary/hold/off
        # per oryx.serving.model-gate.mode — the per-replica half of the
        # fleet controller's canary rollout
        from oryx_tpu.common.modelgate import configure_model_gate

        configure_model_gate(config)
        # healthz up->degraded edge detection (note_health_state): the
        # transition automatically triggers a flight snapshot off-thread
        self._last_health_degraded = False
        self.started_at = time.monotonic()
        self.loop_count = 1  # the async frontend overwrites with its fan-out
        reg = get_registry()
        self._m_requests = reg.counter(
            "oryx_serving_requests_total",
            "Serving requests by method and status",
            labeled=True,
        )
        self._m_latency = reg.histogram(
            "oryx_serving_request_seconds", "Serving request latency by method"
        )
        # label by manager class and hold the app weakly: several ServingApps
        # can coexist in one process (tests, embedders) and the process-global
        # registry must neither pin them alive nor conflate their models
        ref = weakref.ref(self)
        reg.gauge(
            "oryx_serving_model_load_fraction", "Fraction of the model loaded"
        ).set_function(
            lambda: _load_fraction(ref), manager=type(model_manager).__name__
        )
        # model-freshness metrics (oryx_update_to_serve_seconds and
        # friends, common/freshness.py) register on first touch so the
        # serving /metrics page always exposes them
        from oryx_tpu.common.freshness import model_freshness

        model_freshness()
        # adopt the config's retry policy / fault plan (the serving
        # process's bus producer+consumer run under them too) and
        # pre-register the robustness metric families — dashboards need
        # the zero baseline from process start, not a series that pops
        # into existence on the first retry/shed/quarantine event
        from oryx_tpu.common import quarantine, retry
        from oryx_tpu.common.faults import configure_faults, get_injector
        from oryx_tpu.layers import watchdog

        retry.configure_retry(config)
        configure_faults(config)
        retry.ensure_metrics()
        quarantine.ensure_metrics()
        get_injector().ensure_metrics()
        watchdog.ensure_metrics()
        reg.counter(
            "oryx_serving_shed_total",
            "Requests deliberately shed with 503 + Retry-After because a "
            "serving queue was saturated",
        )
        self._load_resources()

    def _load_resources(self) -> None:
        """Import configured resource modules and let them register routes —
        the OryxApplication package-scan equivalent."""
        import importlib

        for mod_name in self.config.get_list("oryx.serving.application-resources", []):
            mod = importlib.import_module(str(mod_name))
            register = getattr(mod, "register", None)
            if register is None:
                raise ValueError(f"resource module {mod_name} has no register(app)")
            register(self)

    def route(self, method: str, pattern: str, nonblocking: bool = False):
        """Register a handler. nonblocking=True declares the handler does
        no blocking work (state lookups + submit_nowait only) — the async
        frontend then runs it INLINE on the event loop instead of paying
        two thread hops through the worker pool per request (measured
        ~25% of the per-request server cost on the serving hot path)."""
        def deco(fn):
            r = _Route(method.upper(), _compile(pattern), fn, nonblocking)
            self.routes.append(r)
            if "{" not in pattern:
                stripped = pattern.strip("/")
                norm = f"/{stripped}" if stripped else "/"
                self._exact_routes.setdefault((r.method, norm), r)
            seg = _first_literal(pattern)
            self._route_index.setdefault(seg, []).append(r)
            # a first segment is "fast" only while EVERY route under it is
            # nonblocking: one blocking sibling poisons the whole segment
            # (the frontend decides before matching the exact route)
            if seg is None:
                # param-first routes are match candidates for EVERY path,
                # so a blocking one disables fast dispatch entirely
                if not nonblocking:
                    self._wildcard_blocking = True
            elif nonblocking and seg not in self._slow_segments:
                self.fast_segments.add(seg)
            else:
                self._slow_segments.add(seg)
                self.fast_segments.discard(seg)
            return fn

        return deco

    def is_fast(self, path: str) -> bool:
        """True when every route that could match `path` is marked
        nonblocking — the async frontend may dispatch inline. Applies the
        same context-path strip as _dispatch so the segment examined is
        the one routing will actually use."""
        if self._wildcard_blocking:
            return False
        if self.context_path:
            if path.startswith(self.context_path + "/"):
                path = path[len(self.context_path):]
            else:
                return False  # context root / outside-context: not hot paths
        first = path.lstrip("/").split("/", 1)[0]
        return first in self.fast_segments

    # -- helpers resources use (AbstractOryxResource equivalents) ----------

    def get_serving_model(self):
        """The loaded model, or 503 until fraction-loaded crosses the
        threshold (AbstractOryxResource.java:75-95). A model past the
        configured staleness bound still serves, but the response carries
        a ``Warning: 110`` header (RFC 7234 "response is stale") so
        clients and probes can see degraded mode."""
        model = self.model_manager.get_model()
        if model is None or model.fraction_loaded() < self.min_fraction:
            raise OryxServingException(503, "model not yet available")
        staleness = self.model_staleness()
        if staleness is not None:
            req = getattr(_current_request, "req", None)
            if req is not None:
                req.response_headers.append((
                    "Warning",
                    f'110 - "stale model: {staleness:.0f}s past publish, '
                    f'bound {self.max_staleness_sec:.0f}s"',
                ))
        return model

    def model_staleness(self) -> float | None:
        """Seconds the served model is past its publish stamp IF that
        exceeds the configured bound, else None (no bound, no stamp yet,
        or fresh). Based on the update-topic publish stamps
        (common/freshness.py), so it measures the pipeline end to end —
        a dead batch layer shows up here even though serving is healthy."""
        if self.max_staleness_sec is None:
            return None
        from oryx_tpu.common.freshness import model_freshness

        f = model_freshness()
        if f.published_ms is None:
            return None  # never stamped: unknown, not provably stale
        age = max(0.0, time.time() * 1000.0 - f.published_ms) / 1000.0
        return age if age > self.max_staleness_sec else None

    def degraded_reasons(self) -> list[str]:
        """Why this serving process is degraded right now (empty = fully
        healthy). The /healthz readiness surface: model past its
        staleness bound, top-k serving failed over to host scoring, or a
        co-resident layer's wedge watchdog tripped.

        In a fleet, each reason carries this replica's identity
        (``model-stale@r1:8101``): a front aggregating N processes' probe
        bodies into one ejection log needs reasons that name the process,
        not anonymous strings N replicas all emit identically."""
        reasons: list[str] = []
        if self.model_staleness() is not None:
            reasons.append("model-stale")
        from oryx_tpu.serving.batcher import TopKBatcher

        b = TopKBatcher._shared  # peek; never construct on a probe path
        if b is not None and b._device_down.is_set():
            reasons.append("device-down")
        from oryx_tpu.layers.watchdog import wedged_layers

        reasons.extend(f"wedged:{name}" for name in wedged_layers())
        if self.replica_id:
            tag = f"@{self.replica_id}:{self.listen_port}"
            reasons = [r + tag for r in reasons]
        return reasons

    def note_health_state(self, degraded: bool, reasons: list[str]) -> None:
        """Edge detector behind the automatic flight snapshot: the FIRST
        probe that sees up→degraded bundles the black box (events, recent
        spans, dispatch ring, metrics, config fingerprint) on a one-shot
        daemon thread — by the time a human looks, the evidence of HOW it
        degraded is already on disk. Called from the (nonblocking)
        healthz handler; the cheap path is two attribute touches."""
        prev = self._last_health_degraded
        self._last_health_degraded = degraded
        if degraded and not prev:
            from oryx_tpu.common.flightrec import get_flightrec

            # record + bundle both happen on the snapshot thread: this
            # handler runs INLINE on the event loop, and the flight dir's
            # disk may be exactly what is degrading
            get_flightrec().snapshot_async(
                "healthz-degraded",
                event={"kind": "health-degraded", "reasons": reasons},
            )

    def staleness_age(self) -> float | None:
        """Raw age in seconds of the served model's publish stamp (None
        until a stamped model loaded) — the number behind
        ``oryx_model_staleness_seconds``, reported on /healthz regardless
        of the degraded bound so a fleet front can watch staleness
        converge per replica instead of only seeing the bound trip."""
        from oryx_tpu.common.freshness import model_freshness

        p = model_freshness().published_ms
        if p is None:
            return None
        return max(0.0, time.time() * 1000.0 - p) / 1000.0

    def send_input(self, line: str) -> None:
        """POST a raw input line to the input topic, keyed by its hash
        (AbstractOryxResource.sendInput). crc32, not hash(): the builtin is
        salted per process (PYTHONHASHSEED), which would make partition
        assignment — and thus cross-partition read interleaving — vary
        between processes; the reference's hashCode partitioner is stable."""
        if self.input_producer is None:
            raise OryxServingException(405, "serving layer is read-only")
        import zlib

        self.input_producer.send(str(zlib.crc32(line.encode("utf-8"))), line)

    # -- dispatch ----------------------------------------------------------

    def dispatch(self, req: Request) -> tuple[int, bytes, str]:
        """Route and render; returns (status, body_bytes, content_type).
        Blocks on deferred handlers — the contract tests and the threaded
        frontend rely on."""
        resp = self.dispatch_nowait(req)
        if isinstance(resp, Deferred):
            resp = resp.future.result()
        return resp

    def dispatch_nowait(self, req: Request):
        """Route and render without blocking on deferred handlers: returns
        either a rendered (status, body, content_type) tuple or a Deferred
        of one (the async frontend awaits it off-thread)."""
        start = time.monotonic()
        # install the request's phase ledger as this thread's current one
        # for the synchronous handler call, so the batcher's submit path
        # attaches it to the pending request without signature threading
        prev_ledger = swap_ledger(req.ledger)
        try:
            if req.trace is not None:
                # install the request span as this thread's current span
                # for the synchronous handler call, so instrumentation
                # below it (batcher submit) parents without signature
                # threading
                prev = swap_current(req.trace)
                try:
                    resp = self._dispatch(req)
                finally:
                    swap_current(prev)
            else:
                resp = self._dispatch(req)
        finally:
            swap_ledger(prev_ledger)
        if isinstance(resp, Deferred):
            rendered: Future = Future()

            def _finish(f):
                try:
                    out = _render(f.result(), req)
                except BaseException as e:  # noqa: BLE001 - boundary
                    out = _render_exception(e, req)
                self._observe(req, start, out[0])
                try_set_result(rendered, out)

            resp.future.add_done_callback(_finish)
            return Deferred(rendered)
        self._observe(req, start, resp[0])
        return resp

    def _observe(self, req: Request, start: float, status: int) -> None:
        # bucket unknown methods: the label is client-controlled and must
        # not grow the process-global registry without bound
        method = req.method if req.method in _KNOWN_METHODS else "OTHER"
        # traced requests leave their trace id as the bucket's exemplar:
        # a latency bucket on /metrics then names a concrete request
        # joinable against /debug/traces (OpenMetrics exemplar syntax)
        trace_id = req.trace.trace_id if req.trace is not None else None
        self._m_latency.observe(
            time.monotonic() - start, trace_id=trace_id, method=method
        )
        self._m_requests.inc(method=method, status=str(status))

    def _dispatch(self, req: Request):
        # thread-current request for the duration of the handler call:
        # helpers without a req in their signature (get_serving_model's
        # stale-model Warning) attach response headers through it
        prev_req = getattr(_current_request, "req", None)
        _current_request.req = req
        try:
            return self._dispatch_routed(req)
        finally:
            _current_request.req = prev_req

    def _dispatch_routed(self, req: Request):
        if self.context_path:
            if req.path == self.context_path:
                req.path = "/"
            elif req.path.startswith(self.context_path + "/"):
                req.path = req.path[len(self.context_path):]
            else:
                return _render_error(
                    404, f"outside context path {self.context_path}", req
                )
        # Literal fast path: a parameterless route resolves with one dict
        # probe and zero regex work (the /recommend-family hot paths are
        # parameterized and take the indexed scan below; /ready, /metrics
        # and the console land here).
        exact = self._exact_routes.get((req.method, req.path))
        if exact is not None:
            req.params = {}
            try:
                result = exact.handler(self, req)
            except Exception as e:  # noqa: BLE001 - boundary: render error
                return _render_exception(e, req)
            if isinstance(result, Deferred):
                return result  # rendered at completion by dispatch_nowait
            return _render(result, req)
        # Precedence contract: literal-first-segment routes match before
        # parameter-first ones; within each group, registration order wins.
        # (This differs from a pure registration-order scan only when a
        # module registers /{param} before a literal sibling — literal
        # specificity winning is the intended behavior, pinned by
        # tests/test_aserver.py::test_route_precedence.)
        first = req.path.lstrip("/").split("/", 1)[0]
        candidates = self._route_index.get(first, ())
        wildcard = self._route_index.get(None, ())
        matched_path = False
        for r in (*candidates, *wildcard):
            m = r.pattern.match(req.path)
            if not m:
                continue
            matched_path = True
            if r.method != req.method:
                continue
            req.params = {k: _unquote(v) for k, v in m.groupdict().items()}
            try:
                result = r.handler(self, req)
            except Exception as e:  # noqa: BLE001 - boundary: render error
                return _render_exception(e, req)
            if isinstance(result, Deferred):
                return result  # rendered at completion by dispatch_nowait
            return _render(result, req)
        if matched_path:
            return _render_error(405, "method not allowed", req)
        return _render_error(404, f"no such endpoint: {req.path}", req)


_KNOWN_METHODS = frozenset({"GET", "HEAD", "POST", "PUT", "DELETE", "PATCH", "OPTIONS"})

# the request being dispatched on this thread (see ServingApp._dispatch)
_current_request = threading.local()


def _load_fraction(app_ref) -> float:
    app = app_ref()
    if app is None:
        raise GaugeSeriesGone("serving app gone")  # render() drops the series
    model = app.model_manager.get_model()
    return model.fraction_loaded() if model is not None else 0.0


def _unquote(s: str) -> str:
    from urllib.parse import unquote

    return unquote(s)


def _wants_json(req: Request) -> bool:
    accept = req.headers.get("accept", "")
    if "application/json" in accept:
        return True
    if "text/csv" in accept or "text/plain" in accept:
        return False
    return True  # default JSON


def _to_csv_rows(value: Any) -> list[list]:
    from oryx_tpu.common.text import join_csv

    if value is None:
        return []
    if isinstance(value, dict):
        return [[k, v] for k, v in value.items()]
    if isinstance(value, (list, tuple)):
        rows = []
        for item in value:
            if isinstance(item, (list, tuple)):
                rows.append(list(item))
            elif isinstance(item, dict):
                rows.append(list(item.values()))
            else:
                rows.append([item])
        return rows
    return [[value]]


def _render(result: Any, req: Request) -> tuple[int, bytes, str]:
    """Serialize one handler result to wire bytes, stamping the ledger's
    serialize phase (both the sync path and deferred completion render
    through here, so the stamp site is single).

    The stamp anchors at the ledger's last phase end, not at render
    entry: on the deferred path the slice between the batcher's device
    fetch and this call — result distribution, the post-processing pool
    hop, top-n trim/ID translation — is host-side result handling, and
    charging it to serialize keeps the phase budget tiling the request
    (>=95% of wall-clock, the attribution contract) instead of leaving
    an invisible gap between device and serialize."""
    if req.ledger is None:
        return _render_body(result, req)
    t0 = time.monotonic()
    tail = req.ledger.last_end()
    start = tail if tail is not None and tail < t0 else t0
    out = _render_body(result, req)
    req.ledger.add("serialize", time.monotonic() - start, start=start)
    return out


def _render_body(result: Any, req: Request) -> tuple[int, bytes, str]:
    if isinstance(result, RawResponse):
        return result.status, result.body, result.content_type
    if result is None:
        return 204, b"", "text/plain"
    if isinstance(result, tuple) and len(result) == 2 and isinstance(result[0], int):
        status, payload = result
        if payload is None:
            return status, b"", "text/plain"
    else:
        status, payload = 200, result
    if _wants_json(req):
        return status, json.dumps(payload).encode("utf-8"), "application/json"
    from oryx_tpu.common.text import join_csv

    rows = _to_csv_rows(payload)
    text = "\n".join(join_csv(r) for r in rows)
    return status, (text + ("\n" if text else "")).encode("utf-8"), "text/csv"


def _render_exception(e: BaseException, req: Request) -> tuple[int, bytes, str]:
    """The ONE error-rendering boundary, shared by sync dispatch and
    deferred completion so status/format behavior cannot drift."""
    if isinstance(e, OryxServingException):
        if e.headers:
            req.response_headers.extend(e.headers)
        return _render_error(e.status, e.message, req)
    return _render_error(500, f"{type(e).__name__}: {e}", req)


def _render_error(status: int, message: str, req: Request) -> tuple[int, bytes, str]:
    """Error body rendering (reference ErrorResource: JSON or plain)."""
    if _wants_json(req):
        body = json.dumps({"status": status, "error": message}).encode("utf-8")
        return status, body, "application/json"
    return status, f"{status} {message}\n".encode("utf-8"), "text/plain"
