"""Shared k-means app pieces: config, datum vectorization, update codec.

Parity notes: vectorization mirrors KMeansUtils.featuresFromTokens
(app/oryx-app-common .../kmeans/KMeansUtils.java) — active schema features
parsed as doubles into predictor order; the UP message is the
`[clusterID, center, count]` JSON of KMeansSpeedModelManager.java:78-120.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import numpy as np

from oryx_tpu.common.config import Config
from oryx_tpu.common.text import parse_input_line
from oryx_tpu.apps.schema import InputSchema


@dataclass
class KMeansConfig:
    init_strategy: str
    eval_strategy: str
    iterations: int
    runs: int
    k: object  # hyperparam range value

    @classmethod
    def from_config(cls, config: Config) -> "KMeansConfig":
        g = lambda key, d=None: config.get(f"oryx.kmeans.{key}", d)
        return cls(
            init_strategy=str(g("initialization-strategy", "k-means||")),
            eval_strategy=str(g("evaluation-strategy", "SILHOUETTE")).upper(),
            iterations=int(g("iterations", 30)),
            runs=int(g("runs", 1)),
            k=g("hyperparams.k", 10),
        )


def vectorize_rows(schema: InputSchema, lines) -> np.ndarray:
    """CSV/JSON lines -> [N,P] float32 predictor matrix; rows with
    unparseable or missing numeric values are dropped (the reference throws
    per-datum and the Spark lambda filters nulls)."""
    out = []
    p = schema.num_predictors
    for line in lines:
        try:
            tok = parse_input_line(line)
        except ValueError:
            continue
        if len(tok) < schema.num_features:
            continue
        row = np.empty(p, dtype=np.float32)
        ok = True
        for j in range(p):
            fi = schema.predictor_to_feature_index(j)
            try:
                row[j] = float(tok[fi])
            except (ValueError, IndexError):
                ok = False
                break
        if ok and not np.isnan(row).any():
            out.append(row)
    return np.stack(out) if out else np.zeros((0, p), dtype=np.float32)


def cluster_update_message(cluster_id: int, center: np.ndarray, count: int) -> tuple[str, str]:
    return "UP", json.dumps(
        [int(cluster_id), [float(v) for v in np.asarray(center)], int(count)]
    )


def parse_cluster_update(message: str) -> tuple[int, np.ndarray, int]:
    arr = json.loads(message)
    return int(arr[0]), np.asarray(arr[1], dtype=np.float64), int(arr[2])
