"""Operational CLI — the oryx-run.sh + deploy/Main.java tier.

Mirrors the reference's command surface (deploy/bin/oryx-run.sh:16-36,
104-119 and the three one-class launchers under deploy/oryx-*/.../Main.java):

  python -m oryx_tpu.cli batch   --conf oryx.conf   run the batch layer
  python -m oryx_tpu.cli speed   --conf oryx.conf   run the speed layer
  python -m oryx_tpu.cli serving --conf oryx.conf   run the serving layer
  python -m oryx_tpu.cli setup   --conf oryx.conf   create the two topics
  python -m oryx_tpu.cli tail    --conf oryx.conf   tail input+update topics
  python -m oryx_tpu.cli input   --conf oryx.conf   stdin lines -> input topic

Where spark-submit/YARN flags would go, there is nothing: processes are
plain Python; multi-chip scale comes from the in-process jax mesh, not a
cluster scheduler. -D-style overrides are --set key=value (the
-Dconfig.file / ConfigToProperties path, oryx-run.sh:90-101,138-139).

`--app <name>` wires a packaged app (als | kmeans | rdf | example |
seq) by registry lookup (oryx_tpu/apps/spi.py): it overlays the app's
batch/speed/serving classes and serving resources underneath any
explicit --set, for every layer subcommand plus fleet/pod.
"""

from __future__ import annotations

import argparse
import json
import logging
import pathlib
import signal
import sys
import time

from oryx_tpu.common.config import Config, load_config


def _parse_args(argv):
    p = argparse.ArgumentParser(prog="oryx_tpu", description=__doc__)
    p.add_argument(
        "command",
        choices=[
            "batch", "speed", "serving", "setup", "tail", "input",
            "import-pmml", "loadtest", "config", "pod", "fleet", "flight",
            "perf",
        ],
    )
    p.add_argument(
        "--app", default=None, metavar="NAME",
        help="packaged app to run (registry lookup, oryx_tpu/apps/spi.py):"
        " als | kmeans | rdf | example | seq. Overlays the app's"
        " batch/speed/serving classes and serving resources underneath any"
        " explicit --set, so `batch|speed|serving|fleet|pod --app seq` all"
        " wire the same app without spelling four class paths",
    )
    p.add_argument(
        "--replicas", type=int, default=None,
        help="fleet: serving replica processes to supervise on this host "
        "(overrides oryx.fleet.replicas)",
    )
    p.add_argument(
        "--front-port", type=int, default=None,
        help="fleet: listening port of the L7 fleet front (overrides "
        "oryx.fleet.front.port)",
    )
    p.add_argument(
        "--policy", choices=["round-robin", "hash"], default=None,
        help="fleet: front placement policy (overrides "
        "oryx.fleet.front.policy; hash = consistent-hash-by-user)",
    )
    p.add_argument(
        "--shards", type=int, default=None,
        help="fleet: device-view shards per replica (overrides "
        "oryx.fleet.shards; the second scaling dimension — replicas x "
        "shards)",
    )
    p.add_argument(
        "--compute", type=int, default=1,
        help="pod: total jax.distributed compute (batch) processes in the "
        "pod across all hosts",
    )
    p.add_argument(
        "--local-start", type=int, default=None,
        help="pod: first compute process index THIS host runs (default: "
        "0 — single-host pod runs all of them)",
    )
    p.add_argument(
        "--local-count", type=int, default=None,
        help="pod: how many compute processes this host runs (default: "
        "all of --compute)",
    )
    p.add_argument(
        "--coordinator",
        help="pod: host:port of compute process 0's coordinator (default: "
        "127.0.0.1:<free port>, valid only for a single-host pod)",
    )
    p.add_argument(
        "--speed", action="store_true",
        help="pod: also run a speed-layer process on this host",
    )
    p.add_argument(
        "--serving", action="store_true",
        help="pod: also run a serving-layer process on this host",
    )
    p.add_argument("--conf", help="user config file (HOCON-like key paths)")
    p.add_argument(
        "--url",
        help="loadtest/perf: base URL of a running serving layer "
        "(default http://localhost:<oryx.serving.api.port>)",
    )
    p.add_argument(
        "--paths",
        help="loadtest: file of request paths to replay round-robin, one "
        "per line (default: stdin; lines like /recommend/u1?howMany=10)",
    )
    p.add_argument(
        "--rate", type=float, default=0.0,
        help="loadtest: target requests/sec, 0 = as fast as possible",
    )
    p.add_argument(
        "--duration", type=float, default=30.0,
        help="loadtest: seconds to run (default 30)",
    )
    p.add_argument(
        "--workers", type=int, default=32,
        help="loadtest: concurrent client connections (default 32)",
    )
    p.add_argument(
        "--http2", action="store_true",
        help="loadtest: speak HTTP/2 (prior knowledge on cleartext, ALPN "
        "over TLS) instead of HTTP/1.1",
    )
    p.add_argument(
        "--loops", type=int, default=None,
        help="serving: async-frontend event-loop threads, each with its "
        "own SO_REUSEPORT listener sharing ONE model (overrides "
        "oryx.serving.api.loops; 0 = one per CPU core)",
    )
    p.add_argument(
        "--sync-mode", choices=["delta", "full", "blocking"], default=None,
        help="serving: how device/host scoring views track live model "
        "updates (overrides oryx.serving.api.sync.mode; delta = "
        "dirty-row scatters applied by a background thread, full = "
        "background snapshot rebuilds, blocking = inline rebuild on the "
        "next query)",
    )
    p.add_argument(
        "--sync-headroom", type=float, default=None,
        help="serving: device-matrix row headroom fraction over the "
        "current store size (overrides "
        "oryx.serving.api.sync.capacity-headroom)",
    )
    p.add_argument(
        "--full-rebuild", action="store_true",
        help="batch: disable incremental generations for this run "
        "(oryx.batch.storage.incremental.enabled=false) — every "
        "generation re-aggregates and cold-trains from all persisted "
        "history, re-anchoring the aggregate snapshot (use after "
        "suspected snapshot corruption or a semantics change)",
    )
    p.add_argument(
        "--trace", action="store_true",
        help="enable request/generation span tracing "
        "(oryx.monitoring.tracing.enabled=true); inspect recorded spans "
        "at GET /debug/traces on the serving layer",
    )
    p.add_argument(
        "--pmml",
        help="PMML file to import (import-pmml): published to the update "
        "topic as a MODEL so running speed/serving layers pick it up",
    )
    p.add_argument(
        "--kind", action="append", default=None, metavar="EVENT_KIND",
        help="flight: only print events of these kinds (repeatable) — "
        "reading a ring for just quality-alarm/ejection events is the "
        "debugging loop those events exist for",
    )
    p.add_argument(
        "--set",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="config override, repeatable (e.g. --set oryx.serving.api.port=8080)",
    )
    return p.parse_args(argv)


def _build_config(args) -> Config:
    overlay = {}
    for kv in args.set:
        if "=" not in kv:
            raise SystemExit(f"--set needs KEY=VALUE, got: {kv}")
        k, v = kv.split("=", 1)
        try:
            overlay[k] = json.loads(v)
        except json.JSONDecodeError:
            overlay[k] = v
    return load_config(args.conf, overlay=overlay)


def _topic_pairs(config: Config) -> list[tuple[str, str, int]]:
    return [
        (
            config.get_string(f"oryx.{t}-topic.broker"),
            config.get_string(f"oryx.{t}-topic.message.topic"),
            config.get_int(f"oryx.{t}-topic.message.partitions", 1),
        )
        for t in ("input", "update")
    ]


def cmd_setup(config: Config) -> int:
    """Create input/update topics (oryx-run.sh kafka-setup)."""
    from oryx_tpu.bus.broker import topics

    for uri, topic, partitions in _topic_pairs(config):
        topics.maybe_create(uri, topic, partitions)
        print(f"ready: {uri} {topic} ({partitions} partitions)")
    return 0


def cmd_tail(config: Config) -> int:
    """Follow both topics, printing topic<TAB>key<TAB>message
    (oryx-run.sh kafka-tail)."""
    from oryx_tpu.bus.broker import get_broker

    pairs = _topic_pairs(config)
    brokers = {uri: get_broker(uri) for uri, _, _ in pairs}
    positions: dict[tuple[str, str, int], int] = {}
    for uri, topic, _ in pairs:
        for part, end in enumerate(brokers[uri].end_offsets(topic)):
            positions[(uri, topic, part)] = end
    stop = []
    signal.signal(signal.SIGINT, lambda *_: stop.append(True))
    while not stop:
        idle = True
        for (uri, topic, part), off in list(positions.items()):
            recs = brokers[uri].read(topic, part, off, 100)
            for o, key, msg in recs:
                print(f"{topic}\t{key}\t{msg}", flush=True)
                positions[(uri, topic, part)] = o + 1
                idle = False
        if idle:
            time.sleep(0.2)
    return 0


def cmd_input(config: Config) -> int:
    """Pump stdin lines into the input topic, keyed by line hash
    (oryx-run.sh kafka-input; keying as AbstractOryxResource.sendInput).
    crc32, not the builtin hash: the builtin is salted per process and
    would shuffle partition assignment between runs."""
    import zlib

    from oryx_tpu.bus.broker import get_broker

    uri, topic, _ = _topic_pairs(config)[0]
    broker = get_broker(uri)
    n = 0
    for line in sys.stdin:
        line = line.rstrip("\n")
        if line:
            broker.send(topic, str(zlib.crc32(line.encode("utf-8"))), line)
            n += 1
    print(f"sent {n} lines to {topic}", file=sys.stderr)
    return 0


def cmd_import_pmml(config: Config, pmml_path: str | None = None) -> int:
    """Migrate a reference-published PMML model: parse it into a native
    artifact and publish it as a MODEL update (the message running
    speed/serving layers already understand)."""
    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.common.pmml import pmml_to_artifact

    if not pmml_path:
        raise SystemExit("import-pmml requires --pmml <file>")
    with open(pmml_path, encoding="utf-8") as f:
        art = pmml_to_artifact(f.read())
    uri, topic, _ = _topic_pairs(config)[1]
    broker = get_broker(uri)
    serialized = art.to_string()
    max_size = config.get_int("oryx.update-topic.message.max-size", 16 * 1024 * 1024)
    if len(serialized.encode("utf-8")) <= max_size:
        broker.send(topic, "MODEL", serialized)
    else:
        # same inline-vs-reference cutover as MLUpdate.publish_model
        # (MLUpdate.java:212-231): oversized models go to the model store
        # and only the path rides the topic
        from oryx_tpu.common.ioutil import strip_scheme

        model_dir = strip_scheme(config.get_string("oryx.batch.storage.model-dir"))
        dest = pathlib.Path(model_dir) / f"imported-{int(time.time() * 1000)}"
        art.write(dest)
        broker.send(topic, "MODEL-REF", str(dest))
    print(f"imported {art.app} model from {pmml_path} -> {topic}", file=sys.stderr)
    return 0


def _apply_platform_env(config: Config | None = None) -> None:
    """Make the platform choice authoritative for framework processes:
    oryx.compute.platform (when not "auto"), overridden by an explicit
    JAX_PLATFORMS env var (the operator's escape hatch).

    Site customizations that pre-register an accelerator PJRT plugin can
    hijack backend resolution so the env var alone is ignored; re-applying
    it through jax.config before any backend is touched restores the
    documented semantics (operators rely on JAX_PLATFORMS=cpu to run a
    layer off-accelerator, e.g. a serving replica on a CPU-only host)."""
    import os

    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms and config is not None:
        configured = config.get_string("oryx.compute.platform", "auto")
        if configured and configured != "auto":
            platforms = configured
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)


def _run_until_interrupt(layer) -> int:
    stop = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, lambda *_: layer.close())
    try:
        layer.start()
        layer.await_termination()
    except KeyboardInterrupt:
        pass
    finally:
        layer.close()
        signal.signal(signal.SIGTERM, stop)
    return 0


def cmd_config(config: Config) -> int:
    """Print the EFFECTIVE config (defaults + user file + overrides) as
    flattened key=value lines — the reference's ConfigToProperties surface
    (deploy/bin/oryx-run.sh:90 pipes it into shell scripts). Globally
    sorted so diffs between deployments are line diffs."""
    from oryx_tpu.common.config import _SECRET_RE

    for path, v in sorted(config.flatten().items()):
        if _SECRET_RE.search(path) and v is not None:
            v = "*****"  # same redaction as Config.pretty
        elif isinstance(v, list):
            v = ",".join(str(x) for x in v)
        elif v is None:
            v = ""
        elif isinstance(v, bool):
            v = str(v).lower()
        print(f"{path}={v}")
    return 0


def cmd_flight(config: Config, kinds: list[str] | None = None) -> int:
    """Print the configured flight-recorder ring as JSONL, oldest first —
    the offline face of GET /debug/flight: works on a CORPSE's dir (the
    process that wrote it need not be alive), so an operator reads a
    crash-looping replica's last words with

        python -m oryx_tpu.cli flight \\
            --set oryx.monitoring.flight.dir=/tmp/oryx_tpu/fleet/r0/flight

    ``--kind`` (repeatable) filters to just those event kinds — the
    incident loop is usually "show me the quality-alarm and ejection
    events", not the whole ring. Unknown kinds fail loudly instead of
    silently printing nothing."""
    from oryx_tpu.common.flightrec import EVENT_KINDS, read_events

    if kinds:
        unknown = sorted(set(kinds) - set(EVENT_KINDS))
        if unknown:
            print(
                f"unknown flight event kind(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(EVENT_KINDS))})",
                file=sys.stderr,
            )
            return 2
    flight_dir = config.get_string(
        "oryx.monitoring.flight.dir", "file:/tmp/oryx_tpu/flight"
    )
    events = read_events(flight_dir)
    total = len(events)
    if kinds:
        wanted = set(kinds)
        events = [ev for ev in events if ev.get("kind") in wanted]
    for ev in events:
        print(json.dumps(ev))
    tail = f" ({total} total)" if kinds else ""
    print(f"# {len(events)} event(s) in {flight_dir}{tail}", file=sys.stderr)
    return 0


# Families the `perf` report reads (common/perfattr.py registers them).
# Suffixed sample names (`_bucket`/`_sum`/`_count`) are built by
# concatenation so each family literal appears once and stays joined to
# the docs/observability.md metric reference table by tools/oryxlint.
_PHASE_FAMILY = "oryx_request_phase_seconds"
_IDLE_FAMILY = "oryx_device_idle_gap_seconds"
_COMPILE_HIST = "oryx_xla_compile_seconds"
_COMPILE_TOTAL = "oryx_xla_compiles_total"


def _parse_metric_sample(
    line: str,
) -> tuple[str, dict[str, str], float] | None:
    """One exposition sample line -> (name, labels, value); None for
    unparseable lines. Exemplars (`... # {...}`) are dropped. Good enough
    for the perfattr families (label values never contain `,` or `#`)."""
    brace = line.find("{")
    space = line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        end = line.find("}", brace)
        if end < 0:
            return None
        name = line[:brace]
        labels: dict[str, str] = {}
        for part in line[brace + 1 : end].split(","):
            k, eq, v = part.partition("=")
            if eq:
                labels[k.strip()] = v.strip().strip('"')
        rest = line[end + 1 :]
    elif space > 0:
        name, labels, rest = line[:space], {}, line[space:]
    else:
        return None
    toks = rest.split("#", 1)[0].split()
    if not toks:
        return None
    try:
        return name, labels, float(toks[0])
    except ValueError:
        return None


def _bucket_quantile(
    buckets: list[tuple[float, float]], q: float
) -> float | None:
    """Nearest-rank quantile estimate from cumulative histogram buckets
    (sorted by upper bound): the upper bound of the bucket holding the
    rank. +Inf means the quantile is beyond the largest finite bound."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    target = q * total
    for bound, cum in buckets:
        if cum >= target:
            return bound
    return buckets[-1][0]


def _fmt_bound_ms(bound: float | None, buckets: list[tuple[float, float]]) -> str:
    if bound is None:
        return "-"
    if bound == float("inf"):
        finite = [b for b, _ in buckets if b != float("inf")]
        return f">{finite[-1] * 1000:.3g}ms" if finite else "inf"
    return f"{bound * 1000:.3g}ms"


def render_perf_report(text: str) -> str:
    """Pure renderer: /metrics exposition text -> the ``oryx perf``
    report (testable without a live replica). Phase p50/p99 are
    bucket-upper-bound estimates, phase share is share of summed phase
    seconds, idle-gap causes rank by total attributed seconds."""
    from oryx_tpu.fleet.observe import parse_exposition

    families, _ = parse_exposition(text)

    def samples(family: str) -> list[tuple[str, dict[str, str], float]]:
        f = families.get(family)
        if f is None:
            return []
        out = []
        for line in f.samples.get("", []):
            parsed = _parse_metric_sample(line)
            if parsed is not None:
                out.append(parsed)
        return out

    lines: list[str] = []

    # -- request phase budget ---------------------------------------------
    buckets: dict[str, list[tuple[float, float]]] = {}
    sums: dict[str, float] = {}
    counts: dict[str, float] = {}
    for name, labels, value in samples(_PHASE_FAMILY):
        phase = labels.get("phase", "")
        if name == _PHASE_FAMILY + "_bucket":
            le = labels.get("le", "+Inf")
            bound = float("inf") if le in ("+Inf", "inf") else float(le)
            buckets.setdefault(phase, []).append((bound, value))
        elif name == _PHASE_FAMILY + "_sum":
            sums[phase] = value
        elif name == _PHASE_FAMILY + "_count":
            counts[phase] = value
    lines.append(f"latency budget ({_PHASE_FAMILY})")
    total_s = sum(sums.values())
    if counts:
        lines.append(
            f"  {'phase':<16}{'count':>8}{'p50':>10}{'p99':>10}{'share':>8}"
        )
        for phase in sorted(
            counts, key=lambda p: sums.get(p, 0.0), reverse=True
        ):
            bs = sorted(buckets.get(phase, []))
            share = sums.get(phase, 0.0) / total_s if total_s else 0.0
            lines.append(
                f"  {phase:<16}{int(counts[phase]):>8}"
                f"{_fmt_bound_ms(_bucket_quantile(bs, 0.50), bs):>10}"
                f"{_fmt_bound_ms(_bucket_quantile(bs, 0.99), bs):>10}"
                f"{share:>7.1%}"
            )
    else:
        lines.append("  (no phase samples yet)")

    # -- device idle gaps --------------------------------------------------
    gap_sums: dict[str, float] = {}
    gap_counts: dict[str, float] = {}
    for name, labels, value in samples(_IDLE_FAMILY):
        cause = labels.get("cause", "")
        if name == _IDLE_FAMILY + "_sum":
            gap_sums[cause] = value
        elif name == _IDLE_FAMILY + "_count":
            gap_counts[cause] = value
    lines.append("")
    lines.append(f"device idle gaps ({_IDLE_FAMILY})")
    gap_total = sum(gap_sums.values())
    if gap_sums:
        lines.append(f"  {'cause':<18}{'gaps':>8}{'total':>12}{'share':>8}")
        for cause in sorted(gap_sums, key=gap_sums.__getitem__, reverse=True):
            share = gap_sums[cause] / gap_total if gap_total else 0.0
            lines.append(
                f"  {cause:<18}{int(gap_counts.get(cause, 0)):>8}"
                f"{gap_sums[cause]:>11.3f}s{share:>7.1%}"
            )
    else:
        lines.append("  (no idle-gap samples yet)")

    # -- XLA compiles ------------------------------------------------------
    comp_n: dict[str, float] = {}
    comp_s: dict[str, float] = {}
    for name, labels, value in samples(_COMPILE_TOTAL):
        if name == _COMPILE_TOTAL:
            comp_n[labels.get("kind", "")] = value
    for name, labels, value in samples(_COMPILE_HIST):
        if name == _COMPILE_HIST + "_sum":
            comp_s[labels.get("kind", "")] = value
    lines.append("")
    lines.append(f"xla compiles ({_COMPILE_TOTAL})")
    if comp_n:
        lines.append(f"  {'kind':<12}{'compiles':>10}{'total':>12}{'mean':>10}")
        for kind in sorted(comp_n):
            n, s = comp_n[kind], comp_s.get(kind, 0.0)
            mean = f"{s / n * 1000:.3g}ms" if n else "-"
            lines.append(
                f"  {kind:<12}{int(n):>10}{s:>11.3f}s{mean:>10}"
            )
    else:
        lines.append("  (no compiles recorded yet)")

    return "\n".join(lines) + "\n"


def cmd_perf(config: Config, url: str | None = None) -> int:
    """Live latency budget of one replica, read from its ``/metrics``:
    phase p50/p99 shares, top idle-gap causes, compile counts — the CLI
    face of the perfattr plane (common/perfattr.py) for an operator
    without a Prometheus in reach:

        python -m oryx_tpu.cli perf --url http://replica-3:8080
    """
    import urllib.request

    base = url or (
        f"http://localhost:{config.get_int('oryx.serving.api.port', 8080)}"
    )
    if "://" not in base:
        base = "http://" + base  # bare host:port
    target = base.rstrip("/") + "/metrics"
    try:
        with urllib.request.urlopen(target, timeout=10) as resp:
            text = resp.read().decode("utf-8", "replace")
    except Exception as e:  # noqa: BLE001 - a report fetch fails as a row
        print(
            f"fetch {target} failed: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return 1
    print(render_perf_report(text), end="")
    return 0


def cmd_batch(config: Config) -> int:
    from oryx_tpu.layers import BatchLayer
    from oryx_tpu.parallel.distributed import (
        configure_compilation_cache, init_distributed,
    )

    configure_compilation_cache(config)
    init_distributed(config)
    return _run_until_interrupt(BatchLayer(config))


def cmd_speed(config: Config) -> int:
    from oryx_tpu.layers import SpeedLayer
    from oryx_tpu.parallel.distributed import (
        configure_compilation_cache, init_distributed,
    )

    configure_compilation_cache(config)
    init_distributed(config)
    return _run_until_interrupt(SpeedLayer(config))


def cmd_serving(config: Config, argv: list[str] | None = None) -> int:
    from oryx_tpu.parallel.distributed import configure_compilation_cache
    from oryx_tpu.serving.server import ServingLayer

    configure_compilation_cache(config)
    n_procs = config.get_int("oryx.serving.api.processes", 1)
    import os

    if n_procs > 1 and not os.environ.get("ORYX_SERVING_REPLICA"):
        return _supervise_serving_replicas(config, n_procs, argv or [])
    return _run_until_interrupt(ServingLayer(config))


def _supervise_serving_replicas(config: Config, n_procs: int, argv: list[str]) -> int:
    """Run N full serving replicas sharing one port via SO_REUSEPORT — the
    kernel load-balances connections, each replica replays the update topic
    into its own model, and per-process GIL ceilings multiply out.

    Requires a fixed port and a cross-process broker (file:// or kafka://;
    mem:// is per-process). Replicas that die are restarted; SIGTERM/INT
    fans out. NOTE: accelerator-backed scoring is per-process — replicas
    on a single-chip host should run with JAX_PLATFORMS=cpu (one chip
    cannot be opened by several processes)."""
    import os
    import subprocess
    import time as _time

    import socket as _socket

    if config.get_int("oryx.serving.api.port", 0) == 0:
        raise SystemExit("oryx.serving.api.processes > 1 requires a fixed port")
    for key in ("oryx.update-topic.broker", "oryx.input-topic.broker"):
        if config.get_string(key, "").startswith("mem://"):
            raise SystemExit(
                f"serving replicas need a cross-process broker; {key} is mem://"
            )
    if not hasattr(_socket, "SO_REUSEPORT"):
        raise SystemExit("serving replicas require SO_REUSEPORT on this platform")

    env = dict(os.environ, ORYX_SERVING_REPLICA="1")
    cmd = [sys.executable, "-m", "oryx_tpu.cli", "serving", *argv]
    procs: list[subprocess.Popen] = []
    stopping = False
    log_ = logging.getLogger(__name__)

    spawn_at: dict[int, float] = {}  # pid -> spawn timestamp

    def spawn() -> subprocess.Popen | None:
        if stopping:
            return None
        p = subprocess.Popen(cmd, env=env)
        spawn_at[p.pid] = _time.monotonic()
        return p

    def shutdown(*_):
        nonlocal stopping
        stopping = True

    old = signal.signal(signal.SIGTERM, shutdown)
    rc_out = 0
    try:
        for _ in range(n_procs):
            p = spawn()
            if p is not None:
                procs.append(p)
        log_.info(
            "serving supervisor: %d replicas on port %d",
            n_procs,
            config.get_int("oryx.serving.api.port", 0),
        )
        consec_fast_fails = 0
        backoff = 1.0
        while not stopping:
            for i, p in enumerate(procs):
                rc = p.poll()
                if rc is not None and not stopping:
                    # a replica that dies within seconds of spawn is a
                    # crash loop (bad config, port conflict): back off,
                    # and give up after repeated immediate failures so
                    # the operator/init system sees a nonzero exit
                    consec_fast_fails += 1
                    if consec_fast_fails >= 3 * n_procs:
                        log_.error(
                            "serving replicas crash-looping (rc=%s); giving up",
                            rc,
                        )
                        stopping = True
                        rc_out = 1
                        break
                    log_.warning(
                        "serving replica died (rc=%s); restarting in %.0fs",
                        rc, backoff,
                    )
                    _time.sleep(backoff)
                    backoff = min(backoff * 2, 30.0)
                    np_ = spawn()
                    if np_ is not None:
                        procs[i] = np_
            now = _time.monotonic()
            if not stopping and all(
                p.poll() is None and now - spawn_at.get(p.pid, now) >= 10.0
                for p in procs
            ):
                # counters clear only once every replica has SURVIVED a
                # while — "alive at the instant of the check" describes
                # a freshly respawned crash-looper too
                consec_fast_fails = 0
                backoff = 1.0
            _time.sleep(1.0)
    except KeyboardInterrupt:
        shutdown()
    finally:
        for p in procs:  # fan out termination even to late spawns
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        signal.signal(signal.SIGTERM, old)
    return rc_out


# every value-taking option of the shared parser: the child-argv
# rebuilders below must know which flags bind the next bare token so the
# SUBCOMMAND token (the first UNBOUND bare token) is identified correctly
_VALUE_OPTS = {
    "--compute", "--local-start", "--local-count", "--coordinator",
    "--conf", "--url", "--paths", "--rate", "--duration", "--workers",
    "--pmml", "--set", "--loops", "--sync-mode", "--sync-headroom",
    "--replicas", "--front-port", "--policy", "--shards", "--app",
}


def _child_flags(
    raw_argv: list[str],
    drop_value_opts: set[str],
    drop_bare_flags: frozenset[str] = frozenset(),
) -> list[str]:
    """Rebuild a child command line from a supervisor invocation: drop the
    SUBCOMMAND token and the supervisor-only flags with their values.
    The subcommand is the first bare token NOT bound as the value of a
    value-taking option — argparse accepts options before the positional,
    so `--conf pod pod --compute 2` must keep --conf's value 'pod' and
    drop the second bare token (round-4 advice: matching the first bare
    'pod' dropped the flag value and left the real subcommand in the
    child argv)."""
    out: list[str] = []
    seen_subcommand = False
    i = 0
    while i < len(raw_argv):
        tok = raw_argv[i]
        name = tok.split("=", 1)[0]
        if name in drop_value_opts:
            # separate-token form consumes its value too; '=' form is one
            i += 2 if tok == name else 1
            continue
        if tok in drop_bare_flags:
            i += 1
            continue
        if tok.startswith("-"):
            out.append(tok)
            if tok == name and name in _VALUE_OPTS and i + 1 < len(raw_argv):
                out.append(raw_argv[i + 1])  # bound value: never subcommand
                i += 2
                continue
            i += 1
            continue
        if not seen_subcommand:  # first UNBOUND bare token: the subcommand
            seen_subcommand = True
            i += 1
            continue
        out.append(tok)
        i += 1
    return out


def _pod_child_flags(raw_argv: list[str]) -> list[str]:
    return _child_flags(
        raw_argv,
        {"--compute", "--local-start", "--local-count", "--coordinator"},
        frozenset(("--speed", "--serving")),
    )


def _fleet_child_flags(raw_argv: list[str]) -> list[str]:
    return _child_flags(
        raw_argv, {"--replicas", "--front-port", "--policy", "--shards"}
    )


def cmd_fleet(config: Config, args, raw_argv: list[str]) -> int:
    """One-host serving fleet: N replica serving processes on distinct
    ports (fleet/supervisor.py) behind the L7 front (fleet/front.py) —
    round-robin or consistent-hash placement, health-driven ejection,
    retry-on-shed. The multi-host shape is the same pieces run per host:
    `serving` with an `oryx.fleet.replica.id` overlay on each host, one
    `fleet` front (or any L7 LB consuming GET /healthz) in front.

        python -m oryx_tpu.cli fleet --conf oryx.conf --replicas 3 \\
            --front-port 8090 --policy hash

    SIGTERM/SIGINT stop the front first (stop taking traffic), then fan
    out to the replicas. Dead replicas are restarted with backoff; a
    crash-looping fleet exits nonzero (docs/operations.md "Running a
    serving fleet")."""
    from oryx_tpu.fleet import FleetController, FleetFront, FleetSupervisor

    overlay = {}
    if args.replicas is not None:
        overlay["oryx.fleet.replicas"] = args.replicas
    if args.front_port is not None:
        overlay["oryx.fleet.front.port"] = args.front_port
    if args.policy is not None:
        overlay["oryx.fleet.front.policy"] = args.policy
    if args.shards is not None:
        overlay["oryx.fleet.shards"] = args.shards
    if overlay:
        config = config.overlay(overlay)
    sup = FleetSupervisor(config, argv=_fleet_child_flags(raw_argv))
    front = None
    controller = None
    prev_term = signal.signal(signal.SIGTERM, lambda *_: sup.request_stop())
    rc = 0
    try:
        sup.start()
        sup.wait_listening(timeout=120)
        front = FleetFront(config, backends=sup.backends())
        front.start()
        # the closed control loop over both: canary rollout + promotion
        # gating when oryx.fleet.canary.enabled, SLO-burn autoscaling
        # when oryx.fleet.autoscale.enabled (a no-op thread otherwise —
        # it still mirrors crash-loop give-ups into /fleet/status)
        controller = FleetController(config, sup, front)
        controller.start()
        print(
            f"fleet: {len(sup.ports())} replicas on ports "
            f"{sup.ports()[0]}..{sup.ports()[-1]}, front :{front.port} "
            f"({front.policy})",
            flush=True,
        )
        rc = sup.run()
    except KeyboardInterrupt:
        pass
    finally:
        if controller is not None:
            controller.close()  # no new rollout/scale decisions mid-teardown
        if front is not None:
            front.close()  # stop taking traffic before killing backends
        sup.stop()
        signal.signal(signal.SIGTERM, prev_term)
    return rc


def cmd_pod(config: Config, args, raw_argv: list[str]) -> int:
    """Multi-host pod launcher — the analogue of the reference's
    oryx-run.sh spark-submit/YARN assembly (deploy/bin/oryx-run.sh:
    199-235), with the cluster plane replaced by a jax.distributed
    process group whose global mesh spans the compute processes.

    One command per host brings up that host's slice of the pod:

      host0$ python -m oryx_tpu.cli pod --conf oryx.conf --compute 4 \\
                 --local-start 0 --local-count 2 \\
                 --coordinator host0:8476 --serving
      host1$ python -m oryx_tpu.cli pod --conf oryx.conf --compute 4 \\
                 --local-start 2 --local-count 2 --coordinator host0:8476

    Compute processes run the batch layer SPMD: each joins the process
    group (cmd_batch -> init_distributed), and the app updates build
    their training mesh over the whole pod (mesh_from_config). The
    speed/serving tiers stay host-local single processes wired only by
    the shared broker — exactly the reference topology, where only the
    Spark batch job spans the cluster and the serving tier scales by
    replicas. Children are supervised: SIGTERM/SIGINT fan out, and any
    compute member dying tears the pod down (a jax.distributed group is
    not elastic — a lost member wedges the collectives, so fail fast).

    Single-host default (no --local-*/--coordinator): all compute
    processes plus the optional tiers run here with an auto-picked
    coordinator port — the smoke topology
    (tests/test_pod_cli.py) and the single-TPU-host deployment.
    """
    import os
    import subprocess

    n_compute = max(1, args.compute)
    local_start = args.local_start if args.local_start is not None else 0
    local_count = (
        args.local_count if args.local_count is not None else n_compute
    )
    if local_start < 0 or local_count < 1:
        raise SystemExit(
            f"pod: --local-start must be >= 0 and --local-count >= 1 "
            f"(got {local_start}, {local_count})"
        )
    if local_start + local_count > n_compute:
        raise SystemExit(
            f"pod: local range [{local_start}, {local_start + local_count})"
            f" exceeds --compute {n_compute}"
        )
    coordinator = args.coordinator
    if coordinator is None:
        if local_start != 0 or local_count != n_compute:
            raise SystemExit(
                "pod: --coordinator is required when this host runs only "
                "part of the pod (process 0's host must be reachable)"
            )
        from oryx_tpu.common.ioutil import choose_free_port

        coordinator = f"127.0.0.1:{choose_free_port()}"

    # child command = this exact invocation minus the pod-only flags,
    # with the role substituted — so --conf/--set/env all carry through
    base_flags = _pod_child_flags(raw_argv)

    def spawn(role: str, extra_sets: list[str]) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "oryx_tpu.cli", role, *base_flags]
        for kv in extra_sets:
            cmd += ["--set", kv]
        return subprocess.Popen(cmd, env=dict(os.environ))

    children: list[tuple[str, subprocess.Popen]] = []
    for pid_idx in range(local_start, local_start + local_count):
        children.append(
            (
                f"compute-{pid_idx}",
                spawn(
                    "batch",
                    [
                        f"oryx.compute.distributed.coordinator-address={coordinator}",
                        f"oryx.compute.distributed.num-processes={n_compute}",
                        f"oryx.compute.distributed.process-id={pid_idx}",
                    ],
                ),
            )
        )
    # speed/serving do NOT join the compute group: force the distributed
    # block back to single-process or init_distributed would park them
    # waiting to be counted as group members
    solo = [
        "oryx.compute.distributed.coordinator-address=null",
        "oryx.compute.distributed.num-processes=1",
        "oryx.compute.distributed.process-id=0",
    ]
    if args.speed:
        children.append(("speed", spawn("speed", solo)))
    if args.serving:
        children.append(("serving", spawn("serving", solo)))

    print(
        f"pod: compute {local_start}..{local_start + local_count - 1} of "
        f"{n_compute} @ {coordinator}"
        + (" + speed" if args.speed else "")
        + (" + serving" if args.serving else ""),
        flush=True,
    )

    stopping = False

    def shut(*_):
        nonlocal stopping
        stopping = True
        for _, c in children:
            if c.poll() is None:
                c.terminate()

    prev_term = signal.signal(signal.SIGTERM, shut)
    rc = 0
    try:
        while True:
            alive = [(n, c) for n, c in children if c.poll() is None]
            if not alive:
                break
            for name, c in children:
                code = c.poll()
                if code is None or stopping:
                    continue
                # ANY compute member exiting — even rc 0 (e.g. someone
                # SIGTERMed one child directly) — must tear the pod down:
                # a jax.distributed group is not elastic, and the
                # survivors would wedge in the next collective forever
                if code != 0 or name.startswith("compute-"):
                    print(
                        f"pod: {name} exited rc={code} — tearing down",
                        file=sys.stderr, flush=True,
                    )
                    rc = 1
                    shut()
                    break
            time.sleep(0.3)
    except KeyboardInterrupt:
        shut()
    finally:
        for _, c in children:
            try:
                c.wait(timeout=15)
            except subprocess.TimeoutExpired:
                c.kill()
                c.wait()
        signal.signal(signal.SIGTERM, prev_term)
    if rc == 0 and any(
        c.returncode not in (0, -signal.SIGTERM.value) for _, c in children
    ) and not stopping:
        rc = 1
    return rc


class _H2LoadConn:
    """Minimal HTTP/2 prior-knowledge (or ALPN-TLS) client for
    `loadtest --http2`: one in-flight stream at a time — the same
    closed-loop-per-worker semantics as the HTTP/1.1 path — reusing the
    serving tier's own HPACK codec (serving/hpack.py)."""

    def __init__(self, host: str, port: int, tls_ctx=None):
        import socket as _socket
        import struct as _struct

        from oryx_tpu.serving.hpack import Decoder, encode

        self._struct = _struct
        s = _socket.create_connection((host, port), timeout=60)
        s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        if tls_ctx is not None:
            s = tls_ctx.wrap_socket(s, server_hostname=host)
            if s.selected_alpn_protocol() != "h2":
                s.close()
                raise ConnectionError(
                    "server did not negotiate h2 over TLS (ALPN: "
                    f"{s.selected_alpn_protocol()!r}) — drop --http2 or "
                    "point at an h2-capable endpoint"
                )
        self._s = s
        self._f = s.makefile("rb", buffering=1 << 16)
        self._dec = Decoder()
        self._encode = encode
        self._authority = f"{host}:{port}".encode()
        self._scheme = b"https" if tls_ctx is not None else b"http"
        self._sid = -1
        s.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
        self._frame(0x4, 0, 0)  # empty SETTINGS

    def _frame(self, ftype: int, flags: int, sid: int, payload: bytes = b"") -> None:
        self._s.sendall(
            self._struct.pack(">I", len(payload))[1:]
            + bytes([ftype, flags])
            + self._struct.pack(">I", sid)
            + payload
        )

    def _read_frame(self):
        head = self._f.read(9)
        if len(head) < 9:
            raise ConnectionError("connection closed")
        ln = int.from_bytes(head[:3], "big")
        payload = self._f.read(ln)
        if len(payload) < ln:
            raise ConnectionError("truncated frame")
        return head[3], head[4], int.from_bytes(head[5:9], "big") & 0x7FFFFFFF, payload

    def get(self, path: str) -> int:
        self._sid += 2
        sid = self._sid
        block = self._encode(
            [
                (b":method", b"GET"),
                (b":scheme", self._scheme),
                (b":path", path.encode()),
                (b":authority", self._authority),
            ]
        )
        self._frame(0x1, 0x5, sid, block)  # END_STREAM | END_HEADERS
        status = 0
        while True:
            ftype, flags, fsid, payload = self._read_frame()
            if ftype == 0x4:  # SETTINGS
                if not flags & 0x1:
                    self._frame(0x4, 0x1, 0)
            elif ftype == 0x1:  # HEADERS
                end_stream = bool(flags & 0x1)  # CONTINUATION never carries it
                while not flags & 0x4:  # collect CONTINUATIONs
                    ct, flags, csid, cp = self._read_frame()
                    if ct != 0x9 or csid != fsid:
                        raise ConnectionError("bad CONTINUATION")
                    payload += cp
                # decode EVERY block in wire order (dynamic-table sync),
                # not just our stream's
                hdrs = dict(self._dec.decode(payload))
                if fsid == sid:
                    status = int(hdrs.get(b":status", b"0"))
                    if end_stream:
                        return status
            elif ftype == 0x0:  # DATA
                end_stream = bool(flags & 0x1)
                if payload:
                    # replenish BOTH windows: the connection's (or long
                    # runs stall at 64KB cumulative) and the stream's (or
                    # any single response > 64KB deadlocks the server
                    # mid-body against the default initial window)
                    inc = self._struct.pack(">I", len(payload))
                    self._frame(0x8, 0, 0, inc)
                    if not end_stream:
                        self._frame(0x8, 0, fsid, inc)
                if fsid == sid and end_stream:
                    return status
            elif ftype == 0x7:  # GOAWAY
                raise ConnectionError("server sent GOAWAY")
            elif ftype == 0x3 and fsid == sid:  # RST_STREAM
                raise ConnectionError("stream reset")
            elif ftype == 0x6 and not flags & 0x1:  # PING
                self._frame(0x6, 0x1, 0, payload)

    def close(self) -> None:
        try:
            self._s.close()
        except OSError:
            pass


def _scrape_serving_metrics(host: str, port: int, tls: bool, prefix: str):
    """Best-effort post-run /metrics scrape: how many frontend event
    loops actually served traffic and the batcher's achieved mean batch
    size. None when the endpoint is unreachable/disabled/authed — the
    loadtest report simply omits the server block then."""
    import http.client
    import re

    try:
        conn = (
            http.client.HTTPSConnection(host, port, timeout=5)
            if tls
            else http.client.HTTPConnection(host, port, timeout=5)
        )
        conn.request("GET", (prefix or "") + "/metrics")
        r = conn.getresponse()
        text = r.read().decode("utf-8", "replace")
        conn.close()
        if r.status != 200:
            return None
    except Exception:
        return None
    loops: dict[str, float] = {}
    mean_batch = None
    for line in text.splitlines():
        m = re.match(r'oryx_http_loop_requests\{loop="(\d+)"\} (\S+)', line)
        if m:
            loops[m.group(1)] = float(m.group(2))
        elif line.startswith("oryx_topk_mean_batch "):
            mean_batch = float(line.split()[1])
    out = {}
    if loops:
        out["loops"] = len(loops)
        out["loops_serving"] = sum(1 for v in loops.values() if v > 0)
        out["loop_requests"] = {k: int(v) for k, v in sorted(loops.items())}
    if mean_batch is not None:
        out["mean_device_batch"] = round(mean_batch, 2)
    return out or None


def cmd_loadtest(config: Config, args) -> int:
    """Replay request paths against a running serving layer at a target
    rate and report throughput + latency percentiles — the operational
    face of the reference's test-tree traffic tools (TrafficUtil +
    LoadBenchmark, app/oryx-app-serving/src/test/.../als/LoadBenchmark.java:
    50-100). Open-loop pacing when --rate is set: request start times are
    scheduled, so queueing delay shows up as latency instead of silently
    shrinking offered load (closed-loop clients do the latter)."""
    import http.client
    import threading
    from urllib.parse import urlsplit

    base = args.url or f"http://localhost:{config.get_int('oryx.serving.api.port', 8080)}"
    if "//" not in base:
        base = "http://" + base  # bare host:port
    split = urlsplit(base)
    if split.scheme not in ("http", "https"):
        raise SystemExit(f"loadtest: unsupported URL scheme {split.scheme!r}")
    tls = split.scheme == "https"
    host = split.hostname or "localhost"
    port = split.port or (443 if tls else 80)
    prefix = split.path.rstrip("/")
    if args.paths:
        lines = [ln.strip() for ln in open(args.paths) if ln.strip()]
    else:
        lines = [ln.strip() for ln in sys.stdin if ln.strip()]
    if not lines:
        raise SystemExit("loadtest: no request paths given")

    n_workers = max(1, args.workers)
    lat_ms: list[list[float]] = [[] for _ in range(n_workers)]
    errors = [0] * n_workers
    t_start = time.perf_counter()
    stop_at = t_start + args.duration
    # open-loop schedule: worker w fires request j at its (j*n+w)/rate slot
    rate = args.rate

    class _H1Conn:
        def __init__(self):
            self._c = (
                http.client.HTTPSConnection(host, port, timeout=60)
                if tls
                else http.client.HTTPConnection(host, port, timeout=60)
            )

        def get(self, path: str) -> int:
            self._c.request("GET", path)
            r = self._c.getresponse()
            r.read()
            return r.status

        def close(self) -> None:
            self._c.close()

    def connect():
        if getattr(args, "http2", False):
            ctx = None
            if tls:
                import ssl

                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
                ctx.set_alpn_protocols(["h2"])
            return _H2LoadConn(host, port, ctx)
        return _H1Conn()

    def worker(w: int) -> None:
        # the h2 client connects eagerly in __init__ (preface+SETTINGS);
        # a refused connect must count as an error and retry, not kill
        # the worker with {"requests": 0, "errors": 0} as the epitaph
        conn = None
        j = 0
        while True:
            now = time.perf_counter()
            if now >= stop_at:
                break
            if conn is None:
                try:
                    conn = connect()
                except Exception:
                    errors[w] += 1
                    time.sleep(0.1)
                    continue
            due = now
            if rate > 0:
                due = t_start + (j * n_workers + w) / rate
                if due >= stop_at:
                    break
                if due > now:
                    time.sleep(due - now)
            path = prefix + lines[(j * n_workers + w) % len(lines)]
            # latency counts from the SCHEDULED slot: when the server (or
            # this worker) falls behind, the slip shows up in the
            # percentiles instead of silently shrinking offered load
            t0 = min(due, time.perf_counter()) if rate > 0 else time.perf_counter()
            try:
                if conn.get(path) == 200:
                    lat_ms[w].append((time.perf_counter() - t0) * 1000)
                else:
                    errors[w] += 1
            except Exception:
                errors[w] += 1
                conn.close()
                conn = None  # reconnect (with error accounting) next loop
            j += 1
        if conn is not None:
            conn.close()

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t_start
    lats = sorted(x for ws in lat_ms for x in ws)
    n_ok, n_err = len(lats), sum(errors)
    if not lats:
        print(json.dumps({"requests": 0, "errors": n_err, "seconds": round(dt, 2)}))
        return 1
    pct = lambda p: round(lats[min(len(lats) - 1, int(p / 100 * len(lats)))], 2)
    report = {
        "requests": n_ok,
        "errors": n_err,
        "seconds": round(dt, 2),
        "qps": round(n_ok / dt, 1),
        "latency_ms": {
            "p50": pct(50), "p90": pct(90), "p99": pct(99),
            "max": round(lats[-1], 2),
        },
        "target_rate": rate or "unlimited",
        "workers": n_workers,
    }
    # server-side view of the same run: loop fan-out coverage + achieved
    # device batch size, so a frontend-scaling regression (one loop doing
    # all the work, batches collapsing to 1) is visible in the report
    server_stats = _scrape_serving_metrics(host, port, tls, prefix)
    if server_stats is not None:
        report["server"] = server_stats
    print(json.dumps(report))
    return 0


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    if args.app is not None:
        # app-registry lookup (apps/spi.py): PREPEND the app's class/
        # resource wiring so any explicit --set still wins, and keep the
        # --app flag itself in argv so replica/fleet/pod children rebuild
        # the same wiring (_child_flags passes value opts through)
        from oryx_tpu.apps.spi import app_overlay

        try:
            overlay = app_overlay(args.app)
        except ValueError as e:
            raise SystemExit(str(e))
        args.set[:0] = [f"{k}={json.dumps(v)}" for k, v in overlay.items()]
    if args.loops is not None:
        # plain config sugar: rides args.set so replica children and pod
        # spawns inherit it like any other override
        args.set.append(f"oryx.serving.api.loops={args.loops}")
    if args.trace:
        # same sugar: tracing propagates to replica/pod children via --set
        args.set.append("oryx.monitoring.tracing.enabled=true")
    if args.full_rebuild:
        args.set.append("oryx.batch.storage.incremental.enabled=false")
    if args.sync_mode is not None:
        args.set.append(f"oryx.serving.api.sync.mode={args.sync_mode}")
    if args.sync_headroom is not None:
        args.set.append(
            f"oryx.serving.api.sync.capacity-headroom={args.sync_headroom}"
        )
    config = _build_config(args)
    _apply_platform_env(config)
    seed = config.get("oryx.test.seed", None)
    if seed is not None:
        # deterministic-run switch (reference RandomManager sysprop)
        from oryx_tpu.common.rng import RandomManager

        RandomManager.use_test_seed(int(seed))
    if args.command == "config":
        return cmd_config(config)
    if args.command == "import-pmml":
        return cmd_import_pmml(config, args.pmml)
    if args.command == "loadtest":
        return cmd_loadtest(config, args)
    if args.command == "pod":
        return cmd_pod(
            config, args, list(argv if argv is not None else sys.argv[1:])
        )
    if args.command == "fleet":
        return cmd_fleet(
            config, args, list(argv if argv is not None else sys.argv[1:])
        )
    if args.command == "serving":
        # replica children re-run this exact command line minus the
        # subcommand token (argparse accepts options BEFORE the
        # positional, so strip the first "serving", wherever it is)
        raw = list(argv if argv is not None else sys.argv[1:])
        raw.remove("serving")
        return cmd_serving(config, raw)
    if args.command == "flight":
        return cmd_flight(config, args.kind)
    if args.command == "perf":
        return cmd_perf(config, args.url)
    return {
        "batch": cmd_batch,
        "speed": cmd_speed,
        "setup": cmd_setup,
        "tail": cmd_tail,
        "input": cmd_input,
    }[args.command](config)


if __name__ == "__main__":
    sys.exit(main())
