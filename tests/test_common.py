"""Unit tests for the common tier (config/rng/text/io/exec/locks/artifact)."""

import json
import threading
import time

import numpy as np
import pytest

from oryx_tpu.common.artifact import ModelArtifact, read_artifact_from_update
from oryx_tpu.common.classutil import load_class, load_instance_of
from oryx_tpu.common.config import Config, ConfigError, default_config, parse_config
from oryx_tpu.common.executil import collect_in_parallel
from oryx_tpu.common.ioutil import (
    choose_free_port,
    delete_older_than,
    list_generation_dirs,
    mkdirs,
    strip_scheme,
    timestamp_from_dirname,
)
from oryx_tpu.common.locks import AutoReadWriteLock, RateLimitCheck
from oryx_tpu.common.rng import RandomManager
from oryx_tpu.common.text import (
    join_csv,
    parse_csv,
    parse_input_line,
)


# ---- config ---------------------------------------------------------------

HOCON = """
# comment
oryx {
  id = "myapp"
  input-topic {
    broker = "mem://test"
    message = { topic = Input, partitions = 4 }
  }
  als.hyperparams.features = [5, 10]
  ref = ${oryx.id}
  interp = "id-${oryx.id}"
  flag = true
}
"""


def test_parse_hocon_subset():
    cfg = parse_config(HOCON)
    assert cfg.get_string("oryx.id") == "myapp"
    assert cfg.get_string("oryx.input-topic.broker") == "mem://test"
    assert cfg.get_int("oryx.input-topic.message.partitions") == 4
    assert cfg.get_list("oryx.als.hyperparams.features") == [5, 10]
    assert cfg.get_string("oryx.ref") == "myapp"
    assert cfg.get_string("oryx.interp") == "id-myapp"
    assert cfg.get_bool("oryx.flag") is True


def test_config_overlay_and_missing():
    cfg = parse_config(HOCON).overlay({"oryx.id": "other", "oryx.new.key": 7})
    assert cfg.get_string("oryx.id") == "other"
    assert cfg.get_int("oryx.new.key") == 7
    # untouched siblings survive the overlay
    assert cfg.get_int("oryx.input-topic.message.partitions") == 4
    with pytest.raises(ConfigError):
        cfg.get("oryx.nope")
    assert cfg.get("oryx.nope", None) is None


def test_config_serialize_roundtrip_and_redaction():
    cfg = parse_config(HOCON).overlay({"oryx.serving.api.password": "hunter2"})
    rt = Config.deserialize(cfg.serialize())
    assert rt.get_string("oryx.id") == "myapp"
    assert "hunter2" not in cfg.pretty()
    assert "*****" in cfg.pretty()


def test_default_config_has_all_layer_keys():
    cfg = default_config()
    for key in [
        "oryx.input-topic.message.topic",
        "oryx.update-topic.message.max-size",
        "oryx.batch.streaming.generation-interval-sec",
        "oryx.speed.min-model-load-fraction",
        "oryx.serving.api.port",
        "oryx.ml.eval.candidates",
        "oryx.als.hyperparams.features",
        "oryx.kmeans.hyperparams.k",
        "oryx.rdf.num-trees",
    ]:
        assert cfg.has(key), key


def test_config_flatten():
    flat = parse_config(HOCON).flatten()
    assert flat["oryx.input-topic.message.topic"] == "Input"


# ---- rng ------------------------------------------------------------------

def test_random_manager_deterministic_under_test_seed():
    RandomManager.use_test_seed(42)
    a = RandomManager.get_random().standard_normal(5)
    RandomManager.use_test_seed(42)
    b = RandomManager.get_random().standard_normal(5)
    np.testing.assert_array_equal(a, b)


def test_random_manager_jax_keys_deterministic():
    import jax

    RandomManager.use_test_seed(7)
    k1 = RandomManager.get_key()
    RandomManager.use_test_seed(7)
    k2 = RandomManager.get_key()
    assert jax.random.uniform(k1) == jax.random.uniform(k2)


# ---- text -----------------------------------------------------------------

def test_csv_roundtrip_with_quoting():
    vals = ["a", 'b,"x"', "", "3.5"]
    line = join_csv(vals)
    assert parse_csv(line) == ["a", 'b,"x"', "", "3.5"]


def test_parse_input_line_json_and_csv():
    assert parse_input_line('["u1","i1","2.5"]') == ["u1", "i1", "2.5"]
    assert parse_input_line("u1,i1,2.5") == ["u1", "i1", "2.5"]


# ---- ioutil ---------------------------------------------------------------

def test_generation_dirs_and_ttl(tmp_path):
    now = int(time.time() * 1000)
    old = now - 10 * 3600 * 1000
    mkdirs(tmp_path / f"oryx-{old}")
    mkdirs(tmp_path / f"oryx-{now}")
    mkdirs(tmp_path / "not-a-generation")
    dirs = list_generation_dirs(tmp_path)
    assert [timestamp_from_dirname(d.name) for d in dirs] == [old, now]
    assert delete_older_than(tmp_path, 5, now_ms=now) == 1
    assert [timestamp_from_dirname(d.name) for d in list_generation_dirs(tmp_path)] == [now]


def test_strip_scheme_and_free_port():
    assert strip_scheme("file:/tmp/x") == "/tmp/x"
    assert strip_scheme("file:///tmp/x") == "/tmp/x"
    assert strip_scheme("/tmp/x") == "/tmp/x"
    assert 0 < choose_free_port() < 65536


# ---- executil / locks -----------------------------------------------------

def test_collect_in_parallel_ordering():
    out = collect_in_parallel(8, lambda i: i * i, parallelism=4)
    assert out == [i * i for i in range(8)]
    assert collect_in_parallel(3, lambda i: i, parallelism=1) == [0, 1, 2]


def test_rw_lock_excludes_writer():
    lock = AutoReadWriteLock()
    events = []

    def writer():
        with lock.write():
            events.append("w")

    with lock.read():
        t = threading.Thread(target=writer)
        t.start()
        time.sleep(0.05)
        assert events == []  # writer blocked while read held
    t.join(2)
    assert events == ["w"]


def test_rate_limit_check():
    rl = RateLimitCheck(period_sec=60)
    assert rl.test() is True
    assert rl.test() is False


# ---- classutil ------------------------------------------------------------

def test_load_class_and_instance():
    assert load_class("oryx_tpu.common.locks.RateLimitCheck") is RateLimitCheck
    inst = load_instance_of("oryx_tpu.common.locks.RateLimitCheck", RateLimitCheck, 5.0)
    assert inst.period == 5.0
    with pytest.raises(ImportError):
        load_class("oryx_tpu.common.locks.Nope")


# ---- artifact -------------------------------------------------------------

def test_artifact_disk_roundtrip(tmp_path):
    art = ModelArtifact(
        "als",
        extensions={"features": "10", "implicit": "true"},
        content={"note": "x"},
        tensors={"X": np.arange(6, dtype=np.float32).reshape(2, 3)},
    )
    art.set_extension("XIDs", ["u1", "u2"])
    d = art.write(tmp_path / "m")
    back = ModelArtifact.read(d)
    assert back.app == "als"
    assert back.get_extension("features") == "10"
    assert back.get_extension_list("XIDs") == ["u1", "u2"]
    np.testing.assert_array_equal(back.tensors["X"], art.tensors["X"])


def test_artifact_string_roundtrip_and_update_decode(tmp_path):
    # the real kmeans artifact shape: centers tensor + counts content
    art = ModelArtifact(
        "kmeans",
        content={"counts": [3]},
        tensors={"centers": np.asarray([[1.0, 2.0]], dtype=np.float32)},
    )
    s = art.to_string()
    back = read_artifact_from_update("MODEL", s)
    assert back.content["counts"] == [3]
    np.testing.assert_allclose(back.tensors["centers"], [[1.0, 2.0]])
    p = art.write(tmp_path / "m2")
    back2 = read_artifact_from_update("MODEL-REF", str(p))
    assert back2.app == "kmeans"
    xml = art.to_pmml_xml()
    assert "ClusteringModel" in xml and "PMML" in xml


def test_artifact_inline_tensors():
    art = ModelArtifact("als", tensors={"Y": np.ones((3, 2), np.float32)})
    back = ModelArtifact.from_string(art.to_string())
    np.testing.assert_array_equal(back.tensors["Y"], np.ones((3, 2), np.float32))
