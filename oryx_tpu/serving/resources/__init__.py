"""Serving REST resources; modules here export register(app) and are named
in oryx.serving.application-resources (the OryxApplication scan analogue).
"""
