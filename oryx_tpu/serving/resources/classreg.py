"""Classification/regression REST endpoints — parity with the reference's
classreg resources (app/oryx-app-serving .../classreg/{Predict,
ClassificationDistribution,FeatureImportance,Train}.java):

  GET  /predict/{datum}                    -> predicted target value
  POST /predict                            -> one prediction per input line
  GET  /classificationDistribution/{datum} -> [value, probability] pairs
  GET  /feature/importance                 -> all predictor importances
  GET  /feature/importance/{index}         -> one predictor's importance
  POST /train  (or /train/{datum})         -> send examples to input topic
"""

from __future__ import annotations

from oryx_tpu.serving.app import OryxServingException, Request, ServingApp


def _predict_or_400(model, datum: str):
    try:
        value, _ = model.predict(datum)
    except (ValueError, KeyError) as e:
        raise OryxServingException(400, f"bad datum: {e}") from None
    return str(value)


def register(app: ServingApp) -> None:
    @app.route("GET", "/predict/{datum}")
    def predict(a: ServingApp, req: Request):
        return _predict_or_400(a.get_serving_model(), req.params["datum"])

    @app.route("POST", "/predict")
    def predict_post(a: ServingApp, req: Request):
        model = a.get_serving_model()
        out = [
            _predict_or_400(model, line.strip())
            for line in req.body_text().splitlines()
            if line.strip()
        ]
        if not out:
            raise OryxServingException(400, "no data points given")
        return out

    @app.route("GET", "/classificationDistribution/{datum}")
    def classification_distribution(a: ServingApp, req: Request):
        model = a.get_serving_model()
        try:
            dist = model.classification_distribution(req.params["datum"])
        except ValueError as e:
            raise OryxServingException(400, str(e)) from None
        return [[value, prob] for value, prob in dist.items()]

    @app.route("GET", "/feature/importance")
    def feature_importance(a: ServingApp, req: Request):
        return a.get_serving_model().feature_importance()

    @app.route("GET", "/feature/importance/{index}")
    def feature_importance_one(a: ServingApp, req: Request):
        importances = a.get_serving_model().feature_importance()
        try:
            return str(importances[int(req.params["index"])])
        except (ValueError, IndexError):
            raise OryxServingException(
                400, f"bad feature index: {req.params['index']}"
            ) from None

    @app.route("POST", "/train/{datum}")
    def train_one(a: ServingApp, req: Request):
        a.send_input(req.params["datum"])
        return 200, None

    @app.route("POST", "/train")
    def train(a: ServingApp, req: Request):
        from oryx_tpu.serving.resources.common import send_input_lines

        send_input_lines(a, req.body_text(), "training examples")
        return 200, None

    def _classreg_console(a: ServingApp) -> list[tuple[str, object]]:
        model = a.get_serving_model()
        imp = model.feature_importance()
        schema = model.schema  # property on RDFServingModel, attr on PMML model
        names = [
            schema.feature_names[schema.predictor_to_feature_index(i)]
            for i in range(len(imp))
        ]
        top = sorted(zip(names, imp), key=lambda t: -t[1])[:5]
        rows: list[tuple[str, object]] = [
            ("target", schema.target_feature),
            ("type", "classification" if schema.is_classification() else "regression"),
        ]
        rows += [(f"importance: {n}", f"{v:.4f}") for n, v in top]
        return rows

    app.console_sections.append(("Forest model", _classreg_console))
