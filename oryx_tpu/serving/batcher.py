"""Request-coalescing micro-batcher for device top-k scoring.

The reference serves each /recommend request by fanning one thread pool
over LSH partitions (ALSServingModel.java:264-279; LoadBenchmark.java
measures ~1-2 concurrent requests saturating a 32-core host). On TPU the
equivalent hot loop is a single [B,K]x[K,I] matmul + top_k — but one
device dispatch per HTTP request wastes the MXU (B=1) and, worse, a
data-dependent k (how_many + len(exclude)) makes every distinct request
shape a fresh XLA compile.

This batcher fixes both:

- Concurrent requests are coalesced into ONE topk_dot_batch dispatch.
  Coalescing is *natural backpressure*, not a timer: while the dispatcher
  thread is busy scoring batch N, new arrivals queue up and become batch
  N+1. An idle server dispatches a single request immediately — no added
  latency floor.
- Shapes are bucketed: the row count pads up to a power of two (zero
  rows) and k rounds up to a fixed bucket, then results are trimmed
  host-side — so the jit cache holds a few dozen entries total instead of
  one per distinct (concurrency, exclusion-set-size) pair.

One process-wide dispatcher is shared across model swaps (serving managers
replace their model object on every MODEL update); requests are grouped by
the identity of the device matrix they score against, so a swap mid-window
simply splits one dispatch into two.

Device-wedge failover: a remote-attached accelerator (this bench host's
tunneled TPU) can wedge so hard that an in-flight host transfer never
returns — not an error, a silent infinite hang, unrecoverable in-process
(round 1's headline failure mode). A watchdog thread detects a dispatch
stuck past ``device_timeout``, fails every parked and queued request over
to host-side numpy scoring (callers pass the row-aligned host matrix the
serving model already keeps for exact re-ranking), and serves degraded
while probing for device recovery in disposable threads. The wedged
dispatcher thread is abandoned — a hung C call cannot be cancelled — and
superseded by a fresh one on recovery (generation check in ``_run``).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future

from oryx_tpu.common import faults
from oryx_tpu.common.perfattr import (
    classify_idle_gap,
    current_ledger,
    get_perfattr,
)
from oryx_tpu.common.perfstats import get_perfstats
from oryx_tpu.common.tracing import current_span, get_tracer
from oryx_tpu.serving.futureutil import try_set_exception, try_set_result

import numpy as np

log = logging.getLogger(__name__)

# process-singleton tracer, bound once: the disabled-tracing submit cost
# is a single attribute read (common/tracing.py)
_TRACER = get_tracer()

# process-singleton dispatch-cost accounting (common/perfstats.py): every
# resolved device group records FLOPs/bytes/wall/occupancy, every host
# fallback zeroes the live MFU window
_PERF = get_perfstats()

# process-singleton latency attribution (common/perfattr.py): per-request
# phase stamps (queue_wait/batch_wait/pad/device/host_fallback), device
# idle-gap classification, and XLA compile telemetry
_PA = get_perfattr()


def _dispatch_bytes(padded: int, features: int, y, kb: int) -> float:
    """Approximate bytes one coalesced dispatch moves: the query upload,
    the item-matrix stream out of HBM (the dominant term — the top-k scan
    is bandwidth-bound in Y), and the result fetch."""
    try:
        y_bytes = float(getattr(y, "nbytes", 0) or 0)
    except Exception:  # non-jax stub matrices in tests
        y_bytes = 0.0
    return float(padded * features * 4 + y_bytes + padded * kb * 8)

from oryx_tpu.ops.als import PALLAS_TOPK_MAX_K

# k rounds up to the smallest of these (then min'd with the item count);
# larger requests fall back to next_pow2(k). A few buckets cover every
# realistic how_many + exclusion overfetch without recompiles. Every
# bucket up to PALLAS_TOPK_MAX_K (the full 128 lane tile since the gen-2
# bitonic kernel) rides the fused Pallas path — a default
# /recommend?howMany=10 overfetches to k=18 and lands in the 32 bucket,
# which bounds the result fetch and host trim below the 128 bucket's.
K_BUCKETS = (16, 32, PALLAS_TOPK_MAX_K, 1024)

MAX_BATCH = 4096  # rows per device dispatch (the bench-measured knee)

# Queue-depth bound before the batcher sheds load (503 + Retry-After via
# serving/app.ShedLoad) instead of queueing without limit. At the default
# the backlog is ~2 full dispatches deep — past that, every queued request
# only adds latency for everyone behind it, and an honest refusal lets
# the client retry against a replica that has capacity.
MAX_QUEUE = 8192

# A dispatch stuck this long is a wedged transport, not a slow kernel —
# EXCEPT while a never-before-dispatched shape may be cold-compiling:
# first dispatches get COMPILE_TIMEOUT grace (a cold XLA compile over a
# remote-compile tunnel runs tens of seconds to minutes, and misreading
# one as a wedge permanently fails the device path over to host scoring).
# Probes re-test a downed device at PROBE_INTERVAL.
DEVICE_TIMEOUT = 75.0
COMPILE_TIMEOUT = 240.0
PROBE_INTERVAL = 20.0

# On an accelerator the top-k scan is HBM-bandwidth-bound in Y: runtime is
# nearly flat in the batch dimension until several hundred rows (at
# 1M x 50f the B=512 matmul adds ~0.1ms on a v5e against the fixed cost of
# streaming Y), so batch shapes pad to just TWO buckets and the pow2
# compile ramp (a dozen cold compiles, tens of seconds each over a
# remote-compile tunnel) collapses to at most two per k-bucket. On CPU the
# sgemm is compute-bound per row: fine-grained pow2 padding keeps wasted
# rows under 2x.
BATCH_BUCKETS_ACCEL = (512, MAX_BATCH)


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _pad_rows(b: int, on_accel: bool) -> int:
    if on_accel:
        for s in BATCH_BUCKETS_ACCEL:
            if b <= s:
                return s
        # a batcher constructed with max_batch beyond the bucket ladder
        # dispatches the group unpadded — padding must never shrink a batch
        return b
    return _next_pow2(b)


def k_bucket(k: int) -> int:
    for b in K_BUCKETS:
        if k <= b:
            return b
    return _next_pow2(k)


def cosine_scale(scores: np.ndarray, norms: np.ndarray) -> np.ndarray:
    """Dot scores -> cosine scores with the shared zero-norm clamp."""
    return scores / np.maximum(norms, 1e-12)


def select_topk(scores: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
    """Top-k (values, indices) of a score vector, ranked descending:
    argpartition then an exact sort of the k survivors. The ONE host
    selection implementation — the batcher fallback and the LSH partition
    path both rank through it, so tie-breaking/NaN semantics can't drift."""
    k = min(k, scores.shape[0])
    top = np.argpartition(-scores, k - 1)[:k]
    top = top[np.argsort(-scores[top])]
    return scores[top], top


def host_topk(
    vec: np.ndarray,
    k: int,
    host_mat: np.ndarray,
    cosine: bool = False,
    norms: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Score one query on the host: f32 matmul + argpartition. The degraded
    path when the accelerator is unavailable — exact, just slower. Pass
    ``norms`` (cached per matrix snapshot) to skip the O(N.K) row-norm pass
    on cosine queries."""
    scores = host_mat @ np.asarray(vec, dtype=np.float32)
    if cosine:
        if norms is None:
            norms = np.linalg.norm(host_mat, axis=1)
        scores = cosine_scale(scores, norms)
    return select_topk(scores, k)


class _Pending:
    __slots__ = (
        "vec", "k", "y", "future", "host_mat", "cosine", "host_norms",
        "recall", "valid_rows", "score_mode", "t_enq", "trace_parent",
        "dev_span", "ledger",
    )

    def __init__(self, vec, k, y, future, host_mat=None, cosine=False,
                 host_norms=None, recall=1.0, valid_rows=None,
                 score_mode="exact"):
        self.vec = vec
        self.k = k
        self.y = y
        self.future = future
        self.host_mat = host_mat
        self.cosine = cosine
        self.host_norms = host_norms
        self.recall = recall
        # rows of y that hold real data: a capacity-padded serving view
        # (apps/als/serving.py) scatter-reserves rows past this for
        # speed-layer growth, and FLOP accounting must not count them
        self.valid_rows = valid_rows
        # which serving score mode produced this request (exact |
        # quantized | approx) — labels the dispatch's perfstats record so
        # per-mode throughput/latency are separable on /metrics
        self.score_mode = score_mode
        # enqueue time: always stamped at submit — the queue_wait phase
        # stamp needs it regardless of tracing. trace_parent/dev_span are
        # only populated while tracing is enabled (the submitting
        # request's span as parent, and a one-element box holding the
        # in-flight device span); ledger is the submitting request's
        # PhaseLedger (common/perfattr.py), or None off the request path
        self.t_enq = 0.0
        self.trace_parent = None
        self.dev_span = None
        self.ledger = None

    def take_dev_span(self):
        """Claim the in-flight device span, exactly once: the dispatcher's
        resolve and the watchdog's host-drain may race to finish it, and
        list.pop is a single GIL-atomic call so only one caller wins (a
        double finish would record the span into two ring slots and
        duplicate its subtree in /debug/traces)."""
        box = self.dev_span
        if not box:
            return None
        try:
            return box.pop()
        except IndexError:
            return None

    def resolve_on_host(self, reason: Exception | None = None) -> bool:
        """Host-score this request. Returns True if a result was delivered,
        False if it could only be failed (no host matrix) — callers count
        host fallbacks from the return value, so errored requests don't
        inflate the degraded-traffic metric."""
        if self.future.done():
            return False
        span = self.take_dev_span()
        if span is not None:
            # the wedged device span ends where host scoring takes over
            _TRACER.finish(span, failover="host")
        if self.host_mat is None:
            try_set_exception(
                self.future,
                reason or RuntimeError("device unavailable, no host fallback"),
            )
            return False
        try:
            tr = _TRACER
            t0 = time.monotonic()
            result = host_topk(
                self.vec, self.k, self.host_mat, self.cosine,
                self.host_norms,
            )
            if self.ledger is not None:
                self.ledger.add(
                    "host_fallback", time.monotonic() - t0, start=t0
                )
            if tr.enabled:
                tr.record_interval(
                    "batcher.host_score", t0, parent=self.trace_parent,
                    k=self.k,
                )
            # a lost try_set race means the wedged dispatcher unwedged
            # mid-drain and delivered its device result first — that
            # request succeeded, just not here
            return try_set_result(self.future, result)
        except Exception as e:  # pragma: no cover - defensive
            try_set_exception(self.future, e)
            return False


class TopKBatcher:
    """Coalesces top-k scoring requests into batched device dispatches."""

    _shared: "TopKBatcher | None" = None
    _shared_lock = threading.Lock()

    @classmethod
    def shared(cls) -> "TopKBatcher":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = TopKBatcher()
        return cls._shared

    def configure(self, config) -> None:
        """Adopt the serving config's shed knobs (ServingLayer.start);
        0 / negative max-queue disables shedding."""
        self.max_queue = config.get_int(
            "oryx.serving.api.shed.max-queue", MAX_QUEUE
        )
        self.retry_after_sec = config.get_int(
            "oryx.serving.api.shed.retry-after-sec", 1
        )

    def __init__(
        self,
        max_batch: int = MAX_BATCH,
        device_timeout: float = DEVICE_TIMEOUT,
        probe_interval: float = PROBE_INTERVAL,
        compile_timeout: float = COMPILE_TIMEOUT,
        max_queue: int = MAX_QUEUE,
        retry_after_sec: int = 1,
    ):
        self.max_batch = max_batch
        self.device_timeout = device_timeout
        self.probe_interval = probe_interval
        self.compile_timeout = compile_timeout
        self.max_queue = max_queue
        self.retry_after_sec = retry_after_sec
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # dispatch shapes that have completed at least once: their XLA
        # compiles are done, so the wedge watchdog needs no compile grace
        self._compiled_shapes: set[tuple] = set()  # guarded-by: _lock
        # shape_key -> grace deadline for NEVER-COMPILED shapes currently
        # in flight: entries are added at dispatch, removed when the
        # dispatch resolves, and cleared on failover — so grace exists
        # exactly while a cold compile may legitimately be running, and a
        # wedge on an already-compiled shape still trips at device_timeout
        self._compiling: dict[tuple, float] = {}  # guarded-by: _lock
        self._on_accel = False
        self._queue: list[_Pending] = []  # guarded-by: _lock
        self._thread: threading.Thread | None = None  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        # watchdog state: _busy_since marks the start of the dispatcher's
        # current device cycle; _inflight holds every request the (possibly
        # wedged) dispatcher owns so the watchdog can fail them over
        self._busy_since: float | None = None  # guarded-by: _lock
        self._inflight: dict[int, _Pending] = {}  # guarded-by: _lock
        self._device_down = threading.Event()
        self._watchdog: threading.Thread | None = None  # guarded-by: _lock
        self._probe_at = 0.0  # guarded-by: _lock
        self._probing = False  # guarded-by: _lock
        self._probe_started = 0.0  # guarded-by: _lock
        self._last_y = None  # guarded-by: _lock
        # idle-gap attribution (common/perfattr.py): _gap_mark is when the
        # device was last known busy (dispatch issued / results fetched);
        # the accumulators hold measured slices of the idle time since —
        # cond waits (empty queue), resolve fetch/distribution tails
        # (host serialize), and down-window backoff. Classified and reset
        # at the next dispatch issue (_launch), reset whenever the device
        # finishes work (_resolve).
        self._gap_mark = time.monotonic()  # guarded-by: _lock
        self._gap_wait = 0.0  # guarded-by: _lock
        self._gap_resolve = 0.0  # guarded-by: _lock
        self._gap_down = 0.0  # guarded-by: _lock
        self._down_since = 0.0  # guarded-by: _lock
        # observability: dispatch count + coalesced-request count let a
        # /metrics scrape compute the achieved mean batch size;
        # host_fallbacks counts requests actually scored on the host.
        # Counters are writes-guarded: scrape-path reads of a monotonic
        # int are advisory by design, but concurrent unlocked increments
        # (a superseded dispatcher racing its replacement) lose updates.
        self.dispatches = 0  # guarded-by: _lock (writes)
        self.coalesced = 0  # guarded-by: _lock (writes)
        self.host_fallbacks = 0  # guarded-by: _lock (writes)
        self.device_failovers = 0  # guarded-by: _lock (writes)
        # analytic FLOPs dispatched to the device (2·B·I·F per group,
        # ops/flops.py): rate(oryx_topk_flops_total) / oryx_device_peak_flops
        # is the serving MFU over any scrape interval
        self.flops_scored = 0.0  # guarded-by: _lock (writes)
        self._peak_flops = ...  # Ellipsis = not yet resolved (see _note_device)
        # tpu device_kind captured once at first dispatch; per-dtype peak
        # cache so a quantized (int8) dispatch divides by the int8 peak,
        # not the bf16 one (ops/flops.py per-dtype tables)
        self._device_kind: str | None = None
        self._peak_by_dtype: dict[str, float | None] = {}

    def register_gauges(self) -> None:
        """Expose the batcher's counters as callback gauges on the global
        metrics registry (the serving layer calls this once at startup;
        scrapes then read live values with no per-scrape mutation)."""
        from oryx_tpu.common.metrics import get_registry

        reg = get_registry()
        for name, help_text, fn in (
            ("oryx_topk_dispatches",
             "device top-k dispatches issued by the micro-batcher",
             lambda: float(self.dispatches)),
            ("oryx_topk_coalesced",
             "requests coalesced into device dispatches",
             lambda: float(self.coalesced)),
            ("oryx_topk_mean_batch",
             "achieved mean coalesced batch size (coalesced/dispatches "
             "over the process lifetime; >1 means requests are sharing "
             "device dispatches)",
             lambda: (
                 self.coalesced / self.dispatches if self.dispatches else 0.0
             )),
            ("oryx_topk_host_fallbacks",
             "requests scored on the host because the device was down",
             lambda: float(self.host_fallbacks)),
            ("oryx_topk_device_failovers",
             "wedged-dispatch failovers declared by the watchdog",
             lambda: float(self.device_failovers)),
            ("oryx_topk_device_down",
             "1 while top-k serving is on the degraded host path",
             lambda: 1.0 if self._device_down.is_set() else 0.0),
            ("oryx_topk_queue_depth",
             "requests waiting for a device dispatch right now; at "
             "oryx.serving.api.shed.max-queue new submits shed with 503",
             # len() is one GIL-atomic read and the depth gauge is
             # advisory; taking the dispatch lock on every scrape would
             # contend with the hot path for a number that is stale the
             # moment it renders
             lambda: float(len(self._queue))),  # oryxlint: disable=guarded-by
            ("oryx_topk_flops_total",
             "analytic FLOPs dispatched to device top-k scoring "
             "(rate over oryx_device_peak_flops = serving MFU)",
             lambda: float(self.flops_scored)),
            ("oryx_device_peak_flops",
             "dense peak FLOP/s of the serving chip at the dtype of the "
             "most recent dispatch (int8/bf16/f32 tables, ops/flops.py; "
             "0 when unknown or not a TPU)",
             lambda: float(self._device_peak() or 0.0)),
        ):
            reg.gauge(name, help_text).set_function(fn)

    def _device_peak(self) -> float | None:
        # NEVER resolve this on the scrape path: jax.devices() initializes
        # the backend, and on a wedged remote transport that hangs forever
        # — a /metrics GET must not be able to wedge the server (verified
        # the hard way on this host). _note_device() fills it in from an
        # array that is already on-device at dispatch time.
        return None if self._peak_flops is ... else self._peak_flops

    def _note_device(self, y) -> None:
        if self._peak_flops is not ...:
            return
        try:
            d = next(iter(y.devices()))
            self._on_accel = getattr(d, "platform", "cpu") not in ("cpu",)
            if getattr(d, "platform", "") == "tpu":
                from oryx_tpu.ops.flops import peak_flops_for_kind

                self._device_kind = getattr(d, "device_kind", "") or ""
                self._peak_flops = peak_flops_for_kind(self._device_kind)
            else:
                self._peak_flops = None
        except Exception:  # non-jax stub matrices in tests
            self._peak_flops = None
        # hand the resolved chip peak to the live-MFU accounting (it must
        # never resolve jax.devices() itself on a scrape path)
        _PERF.note_peak("serving", self._device_peak())

    def _peak_for_matrix(self, y) -> float | None:
        """Chip peak at the dtype this dispatch actually streams (int8 for
        a QuantizedMatrix, bf16/f32 otherwise) — cached per dtype, resolved
        from the device kind _note_device captured. The live MFU gauge's
        denominator follows the most recent dispatch's dtype; a quantized
        deployment therefore reads against the int8 peak, never flattering
        itself against bf16."""
        if self._device_kind is None:
            return self._peak_flops if self._peak_flops is not ... else None
        from oryx_tpu.ops.flops import normalize_dtype, peak_flops_for_kind

        dtype = normalize_dtype(str(getattr(y, "dtype", "") or "bfloat16"))
        peak = self._peak_by_dtype.get(dtype, ...)
        if peak is ...:
            peak = peak_flops_for_kind(self._device_kind, dtype)
            self._peak_by_dtype[dtype] = peak
        self._peak_flops = peak  # the oryx_device_peak_flops gauge tracks it
        return peak

    # -- public API --------------------------------------------------------

    def submit(
        self,
        vec: np.ndarray,
        k: int,
        y,
        host_mat: np.ndarray | None = None,
        cosine: bool = False,
        host_norms: np.ndarray | None = None,
        recall: float = 1.0,
        valid_rows: int | None = None,
        score_mode: str = "exact",
    ) -> tuple[np.ndarray, np.ndarray]:
        """Score vec against device matrix y, returning (values, indices)
        for the top-k rows. Blocks until the coalesced dispatch completes.

        host_mat (the row-aligned f32 host copy of y) enables degraded
        host-side scoring when the device transport is wedged; host_norms
        caches its row norms for cosine fallbacks. recall < 1 selects the
        approximate device kernel (host fallback stays exact). valid_rows
        marks the real-data prefix of a capacity-padded matrix (FLOP
        accounting only; the caller filters padding indices from results).
        score_mode labels the dispatch's perfstats record (exact |
        quantized | approx) for per-mode observability.
        """
        return self.submit_nowait(
            vec, k, y, host_mat=host_mat, cosine=cosine,
            host_norms=host_norms, recall=recall, valid_rows=valid_rows,
            score_mode=score_mode,
        ).result()

    def submit_nowait(
        self,
        vec: np.ndarray,
        k: int,
        y,
        host_mat: np.ndarray | None = None,
        cosine: bool = False,
        host_norms: np.ndarray | None = None,
        recall: float = 1.0,
        valid_rows: int | None = None,
        score_mode: str = "exact",
    ) -> Future:
        """submit() without the wait: returns the Future of (values,
        indices). Deferred endpoints chain post-processing onto it instead
        of parking a worker thread per in-flight request."""
        vec = np.asarray(vec, dtype=np.float32)
        fut: Future = Future()
        p = _Pending(
            vec, int(k), y, fut, host_mat, cosine, host_norms,
            float(recall), valid_rows, score_mode,
        )
        # queue-wait measures from here to the dispatcher picking the
        # batch up; the ledger is the submitting request's (thread-local,
        # installed by ServingApp.dispatch_nowait — None off the request
        # path, e.g. bench/probe submits)
        p.t_enq = time.monotonic()
        p.ledger = current_ledger()
        if p.ledger is not None:
            # the slice between the last stamped phase (parse/auth) and
            # this enqueue is routing + handler pre-work building the
            # query (model lookup, user-vector fetch) — charge it to
            # parse so the budget keeps tiling the request wall-clock
            # instead of leaking it between auth and queue_wait
            tail = p.ledger.last_end()
            if tail is not None and tail < p.t_enq:
                p.ledger.add("parse", p.t_enq - tail, start=tail)
        if _TRACER.enabled:
            # parent = the submitting request's span (thread-current, set
            # by ServingApp.dispatch_nowait)
            p.trace_parent = current_span()
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self.max_queue > 0 and len(self._queue) >= self.max_queue:
                # saturation: refuse honestly instead of queueing without
                # bound. Raised under the lock so the depth check and the
                # refusal are one decision; the exception renders as
                # 503 + Retry-After at the app boundary.
                from oryx_tpu.common.flightrec import get_flightrec
                from oryx_tpu.common.metrics import get_registry
                from oryx_tpu.serving.app import ShedLoad

                get_registry().counter("oryx_serving_shed_total").inc()
                # flight EPISODE marker: one bounded disk append per 5s
                # per storm (the episode_s gate is a dict probe on every
                # other shed), so the black box records that a shed storm
                # happened without per-request I/O under this lock
                get_flightrec().record(
                    kind="shed-episode", episode_s=5.0,
                    queue_depth=len(self._queue),
                )
                raise ShedLoad(
                    f"top-k queue saturated ({len(self._queue)} deep)",
                    retry_after_sec=self.retry_after_sec,
                )
            # the down-check must happen under the lock: a check-then-queue
            # race against the watchdog's failover would park this request
            # on a wedged device with nothing left to fail it over
            down = self._device_down.is_set()
            # refresh the probe target every submit: recovery must test the
            # matrix that will actually be served, and holding only the
            # last-DISPATCHED y would pin a swapped-out model's device
            # buffer for the whole outage
            self._last_y = y
            if not down:
                self._ensure_thread()
                self._ensure_watchdog()
                self._queue.append(p)
                self._cond.notify()
        if down:
            self._maybe_probe()
            if p.resolve_on_host():
                with self._lock:
                    self.host_fallbacks += 1
                _PERF.note_fallback(1)
        return fut

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=5)
        with self._lock:
            self._last_y = None

    # -- dispatcher --------------------------------------------------------

    def _ensure_thread(self) -> None:  # oryxlint: holds=_lock
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="oryx-topk-batcher", daemon=True
            )
            self._thread.start()

    def _ensure_watchdog(self) -> None:  # oryxlint: holds=_lock
        if self._watchdog is None or not self._watchdog.is_alive():
            self._watchdog = threading.Thread(
                target=self._watch, name="oryx-topk-watchdog", daemon=True
            )
            self._watchdog.start()

    def _run(self) -> None:  # oryxlint: offloop (dedicated dispatcher thread)
        # Depth-1 pipeline: launch batch N+1's device work (with async
        # device->host copies) BEFORE materializing batch N's results. A
        # blocking fetch without a prior copy_to_host_async costs a full
        # synchronous transport round trip — measured 2600 ms (!) for a
        # B=1 dispatch on the tunneled TPU vs 38 ms pipelined — so the
        # overlap is not an optimization, it is the difference between a
        # usable and an unusable serving tier on remote-attached devices.
        me = threading.current_thread()
        inflight: list[tuple[list[_Pending], int, object, object, tuple, tuple]] = []
        while True:
            with self._cond:
                while not self._queue and not self._closed and not inflight:
                    t_w = time.monotonic()
                    self._cond.wait()
                    # empty-queue idle accounting for the gap classifier
                    self._gap_wait += time.monotonic() - t_w
                if self._closed and not self._queue and not inflight:
                    return
                if self._thread is not me:
                    # superseded after a wedge: a fresh dispatcher owns the
                    # queue now; whatever this one still holds was already
                    # failed over by the watchdog
                    return
                batch, self._queue = self._queue[: self.max_batch], self._queue[self.max_batch:]
                for p in batch:
                    self._inflight[id(p)] = p
                self._busy_since = time.monotonic()
            try:
                launched = self._launch(batch) if batch else []
            except Exception as e:  # pragma: no cover - defensive: a failure
                # before the per-group guard (grouping, imports) must fail
                # the whole batch, not kill the thread with futures pending
                log.exception("batcher launch failed")
                for p in batch:
                    try_set_exception(p.future, e)
                launched = []
            for item in inflight:
                self._resolve(item)
            with self._cond:
                if self._thread is not me:
                    # superseded mid-cycle: the replacement dispatcher owns
                    # _busy_since now — wiping it would blind the watchdog
                    # to the replacement's own wedge
                    return
                self._busy_since = None
                for item in inflight:
                    for p in item[0]:
                        self._inflight.pop(id(p), None)
                for p in batch:
                    if p.future.done():
                        self._inflight.pop(id(p), None)
            inflight = launched

    def _launch(
        self, batch: list[_Pending]
    ) -> list[tuple[list[_Pending], int, object, object, tuple, tuple]]:
        """Issue one device dispatch per (matrix, k-bucket) group and start
        the async result copies; returns the in-flight group handles."""
        import jax.numpy as jnp

        from oryx_tpu.ops.als import topk_dot_batch

        tr = _TRACER
        # queue-wait ends now: the dispatcher owns the batch
        t_pick = time.monotonic()
        for p in batch:
            if p.ledger is not None and p.t_enq:
                p.ledger.add("queue_wait", t_pick - p.t_enq, start=p.t_enq)
        if tr.enabled:
            for p in batch:
                if p.t_enq:
                    tr.record_interval(
                        "batcher.queue_wait", p.t_enq, t_pick,
                        parent=p.trace_parent,
                    )

        groups: dict[tuple[int, int, float], list[_Pending]] = {}
        for p in batch:
            n = p.y.shape[0]
            kb = min(k_bucket(p.k), n)
            groups.setdefault((id(p.y), kb, p.recall), []).append(p)

        # under the lock: a wedged-then-unwedged dispatcher can overlap
        # its replacement here, and unlocked += loses updates
        # [oryxlint guarded-by fix]
        with self._lock:
            self.dispatches += len(groups)
            self.coalesced += len(batch)

        launched = []
        gap_pending = True  # classify the inter-dispatch idle gap once
        for (_, kb, recall), group in groups.items():
            # failures stay inside their group: a bad shape / OOM against
            # one target matrix must not fail requests scoring another
            shape_key = None
            try:
                faults.fire("serving.device")
                t0 = time.monotonic()
                y = group[0].y
                b = len(group)
                # a capacity-padded serving view scores zero rows past
                # valid_rows — they're HBM-cheap but not useful FLOPs, so
                # the MFU figure counts only the real-data prefix
                n_rows = group[0].valid_rows or y.shape[0]
                group_flops = 2.0 * b * n_rows * y.shape[1]
                self._note_device(y)
                # per-dtype peak: a quantized (int8) dispatch's MFU window
                # divides by the int8 peak, an exact bf16 one by bf16
                _PERF.set_peak("serving", self._peak_for_matrix(y))
                padded = _pad_rows(b, self._on_accel)
                # keyed on the FULL (capacity) shape: the serving view
                # pads rows up a bucket ladder precisely so store growth
                # keeps hitting these compiled entries
                shape_key = (
                    padded, kb, recall, tuple(y.shape),
                    str(getattr(y, "dtype", "")),
                )
                first_compile = False
                with self._cond:
                    # recovery probes re-test against the latest matrix;
                    # the probe thread reads it under the same lock
                    # [oryxlint guarded-by fix: these three were unlocked]
                    self._last_y = y
                    self.flops_scored += group_flops
                    if shape_key not in self._compiled_shapes:
                        # first dispatch of this shape may cold-compile for
                        # minutes over a remote-compile tunnel: give the
                        # wedge watchdog compile grace (for THIS shape,
                        # until it resolves) so it doesn't misread the
                        # compile as a wedged transport and permanently
                        # fail the device path over to host scoring
                        first_compile = True
                        self._compiling[shape_key] = (
                            time.monotonic() + self.compile_timeout
                        )
                for p in group:
                    if p.ledger is not None:
                        # picked -> this group starts forming
                        p.ledger.add("batch_wait", t0 - t_pick, start=t_pick)
                t_pad = time.monotonic()
                xs = np.zeros((padded, y.shape[1]), dtype=np.float32)
                for i, p in enumerate(group):
                    xs[i] = p.vec
                pad_s = time.monotonic() - t_pad
                for p in group:
                    if p.ledger is not None:
                        p.ledger.add("pad", pad_s, start=t_pad)
                if tr.enabled:
                    # device span: dispatch issue until the host fetch
                    # resolves (_resolve); one span per request so every
                    # request's trace tree shows its own device time
                    for p in group:
                        if p.t_enq:
                            p.dev_span = [tr.start(
                                "batcher.device", parent=p.trace_parent,
                                k=kb, batch=b, rows=padded,
                            )]
                t_disp = time.monotonic()
                if gap_pending:
                    # the idle gap between the previous dispatch finishing
                    # and this one being issued, split by measured cause
                    gap_pending = False
                    with self._lock:
                        gap = t_disp - self._gap_mark
                        causes = classify_idle_gap(
                            gap, wait_s=self._gap_wait,
                            serialize_s=self._gap_resolve,
                            down_s=self._gap_down,
                        )
                        self._gap_wait = 0.0
                        self._gap_resolve = 0.0
                        self._gap_down = 0.0
                        self._gap_mark = t_disp
                    for cause, s in causes.items():
                        _PA.record_idle_gap(cause, s)
                vals, idx = topk_dot_batch(
                    jnp.asarray(xs), y, k=kb, recall=recall
                )
                try:
                    vals.copy_to_host_async()
                    idx.copy_to_host_async()
                except AttributeError:  # non-jax array (tests with stubs)
                    pass
                t_issued = time.monotonic()
                with self._lock:
                    # the device is busy from here: the next idle gap
                    # starts when its results land (_resolve)
                    self._gap_mark = max(self._gap_mark, t_issued)
                if first_compile:
                    # the jit call traces+compiles synchronously on the
                    # first dispatch of a shape, then enqueues: the call
                    # duration IS the compile stall (a warm call returns
                    # in microseconds). Feed the compile telemetry, charge
                    # the stall to the device's idle account, and mark it
                    # as a distinct waterfall span — the first dispatch
                    # after a generation swap lands here by construction
                    # (a new matrix identity is a new shape signature).
                    compile_s = t_issued - t_disp
                    _PA.record_compile("serving", compile_s)
                    _PA.record_idle_gap("compile_stall", compile_s)
                    if tr.enabled:
                        tr.record_interval(
                            "batcher.compile_stall", t_disp, t_issued,
                            parent=group[0].trace_parent,
                            k=kb, rows=padded,
                        )
                # per-dispatch cost accounting, finalized at resolve time
                # (wall-clock runs dispatch → host fetch materialized):
                # occupancy = real rows / the capacity-padded view shape
                tp = group[0].trace_parent
                cost = (
                    t0, group_flops,
                    _dispatch_bytes(padded, y.shape[1], y, kb),
                    b, padded, int(n_rows), int(y.shape[0]),
                    tp.trace_id if tp is not None else None,
                    group[0].score_mode,
                    t_disp,
                )
                launched.append((group, kb, vals, idx, shape_key, cost))
            except Exception as e:
                log.exception("batcher group dispatch failed (k=%d)", kb)
                # no compile is in flight anymore: drop the grace entry,
                # or a real transport wedge on a compiled shape would sit
                # behind this shape's stale compile deadline
                if shape_key is not None:
                    with self._cond:
                        self._compiling.pop(shape_key, None)
                self._fail_group_over(group, e)
        return launched

    def _fail_group_over(self, group: list[_Pending], e: Exception) -> None:
        """A device dispatch/transfer ERROR (not a wedge — the watchdog
        owns those): serve the group exactly on the host instead of
        failing it. Requests without a host matrix get the error; the
        watchdog's concurrent drain may be host-resolving these same
        futures, and resolve_on_host/try_set absorb the lost race."""
        n = 0
        for p in group:
            if p.host_mat is not None:
                if p.resolve_on_host(e):
                    n += 1
            else:
                span = p.take_dev_span()
                if span is not None:
                    _TRACER.finish(span, error=type(e).__name__)
                try_set_exception(p.future, e)
        if n:
            with self._lock:
                self.host_fallbacks += n
            # visible degraded-mode accounting: count the host dispatches
            # and zero the live MFU window — host throughput during the
            # outage must not read as healthy device utilization
            _PERF.note_fallback(n)

    def _resolve(
        self, item: tuple[list[_Pending], int, object, object, tuple, tuple]
    ) -> None:
        group, kb, vals_dev, idx_dev, shape_key, cost = item
        try:
            vals = np.asarray(vals_dev)
            idx = np.asarray(idx_dev)
            t_fetch = time.monotonic()
            # results are on the host: the dispatch's device work + fetch
            # is complete — record its cost (FLOPs/bytes/wall/occupancy)
            # into the live perf accounting
            (t0, flops, bytes_moved, b, padded, valid, cap, trace_id,
             mode, t_disp) = cost
            _PERF.record_dispatch(
                "serving",
                flops=flops, bytes_moved=bytes_moved,
                wall_s=t_fetch - t0, rows=b, padded_rows=padded,
                valid_rows=valid, capacity_rows=cap, trace_id=trace_id,
                t_start=t0, score_mode=mode,
            )
            # the dispatch completed, so this shape's compile is done:
            # drop its grace window and never grant it one again. Both
            # under the lock — the watchdog iterates _compiling.values()
            # holding it (an unlocked pop mid-iteration kills the watchdog
            # thread with RuntimeError), and _launch's membership probe of
            # _compiled_shapes reads under it too
            with self._cond:
                self._compiled_shapes.add(shape_key)
                self._compiling.pop(shape_key, None)
                # the device finished this dispatch when the fetch landed:
                # the next idle gap starts here. Earlier accumulator
                # slices predate the device finishing — outside the new
                # gap window by construction — so they reset with it.
                if t_fetch > self._gap_mark:
                    self._gap_mark = t_fetch
                    self._gap_wait = 0.0
                    self._gap_resolve = 0.0
                    self._gap_down = 0.0
            for i, p in enumerate(group):
                k_eff = min(p.k, kb)
                span = p.take_dev_span()
                if span is not None:
                    _TRACER.finish(span)
                if p.ledger is not None:
                    # dispatch issue -> results fetched to host
                    p.ledger.add("device", t_fetch - t_disp, start=t_disp)
                # the watchdog may have host-resolved this request while the
                # fetch above sat on a wedged transport — and may win the
                # race BETWEEN a done() check and the set; try_set absorbs
                # the lost race instead of failing the rest of the group
                try_set_result(p.future, (vals[i, :k_eff], idx[i, :k_eff]))
            with self._lock:
                # result-distribution tail: host work the device idles
                # behind (the host_serialize slice of the next gap)
                self._gap_resolve += time.monotonic() - t_fetch
        except Exception as e:
            log.exception("batcher group resolve failed (k=%d)", kb)
            with self._cond:
                self._compiling.pop(shape_key, None)
            # a device->host transfer ERROR degrades to host scoring like
            # a dispatch error does (wedges — hangs — stay the watchdog's)
            self._fail_group_over(group, e)

    # -- watchdog: wedged-transport failover -------------------------------

    def _watch(self) -> None:  # oryxlint: offloop (watchdog thread)
        while True:
            time.sleep(min(1.0, self.device_timeout / 4))
            with self._cond:
                if self._closed:
                    return
                busy = self._busy_since
                now = time.monotonic()
                wedged = (
                    busy is not None
                    and now - busy > self.device_timeout
                    # a first-dispatch shape may still be cold-compiling:
                    # grace holds only while such a shape is in flight and
                    # its own compile deadline hasn't passed
                    and now > max(self._compiling.values(), default=0.0)
                )
                if not wedged:
                    continue
                # Fail over: mark the device down FIRST so new submits take
                # the host path, then resolve everything the wedged
                # dispatcher owns plus the whole queue on the host.
                self.device_failovers += 1
                self._device_down.set()
                self._down_since = now  # idle-gap failover_backoff window
                self._probe_at = time.monotonic() + self.probe_interval
                stuck = list(self._inflight.values()) + self._queue
                self._inflight.clear()
                self._queue = []
                self._busy_since = None
                self._compiling.clear()  # abandoned with the dispatcher
                self._thread = None  # supersede the wedged dispatcher
            log.error(
                "device dispatch stuck > %.0fs — failing %d requests over "
                "to host scoring and marking the device down",
                self.device_timeout,
                len(stuck),
            )
            err = RuntimeError(
                f"device dispatch exceeded {self.device_timeout}s"
            )

            # drain concurrently: serial host scoring of a MAX_BATCH-deep
            # backlog would add minutes of extra wait on top of the
            # timeout the callers already paid
            def _drain(chunk: list[_Pending]) -> None:
                n = 0
                for p in chunk:
                    if p.resolve_on_host(err):
                        n += 1
                with self._lock:
                    self.host_fallbacks += n
                _PERF.note_fallback(n)

            n_threads = min(8, max(1, len(stuck) // 32 + 1))
            if n_threads == 1:
                _drain(stuck)
            else:
                drains = [
                    threading.Thread(
                        target=_drain, args=(stuck[i::n_threads],),
                        name=f"oryx-topk-drain-{i}", daemon=True,
                    )
                    for i in range(n_threads)
                ]
                for t in drains:
                    t.start()
                for t in drains:
                    t.join()

    def _maybe_probe(self) -> None:
        """While the device is down, periodically test it with a tiny
        dispatch in a disposable thread (a probe into a wedged transport
        hangs forever — it must never block a request path). On success the
        device path resumes."""
        with self._lock:
            if (
                self._probing
                and time.monotonic() - self._probe_started > self.device_timeout
            ):
                # the probe itself hung on the wedged transport; abandon it
                # (its thread can never be cancelled) or no probe would
                # ever run again and the device path could never resume
                self._probing = False
            if (
                self._probing
                or self._last_y is None
                or time.monotonic() < self._probe_at
            ):
                return
            self._probing = True
            self._probe_started = time.monotonic()
            y = self._last_y

        def probe() -> None:  # oryxlint: offloop (disposable probe thread)
            ok = False
            try:
                from oryx_tpu.ops.als import topk_dot_batch

                z = np.zeros((1, y.shape[1]), dtype=np.float32)
                import jax.numpy as jnp

                vals, idx = topk_dot_batch(jnp.asarray(z), y, k=1)
                np.asarray(idx)
                ok = True
            except Exception:
                log.info("device probe failed; staying on host path")
            with self._lock:
                self._probing = False
                self._probe_at = time.monotonic() + self.probe_interval
                if ok and self._device_down.is_set():
                    log.warning("device probe succeeded — resuming device path")
                    self._device_down.clear()
                    if self._down_since:
                        # the whole down window was device idle by fiat:
                        # charge it to failover_backoff in the next gap
                        self._gap_down += time.monotonic() - self._down_since
                        self._down_since = 0.0

        threading.Thread(
            target=probe, name="oryx-topk-probe", daemon=True
        ).start()
