"""Self-describing model artifact — the PMML equivalent.

The reference interchanges models as PMML documents whose *extensions* act as
a generic key/value channel (PMMLUtils.java:55-135, AppPMMLUtils.java:67-280):
ALS publishes a skeleton PMML holding only hyperparams + factor-file paths,
k-means a real ClusteringModel, RDF a MiningModel of TreeModels. Here the
artifact is JSON metadata (+ optional npz tensor payloads) — a format XLA-side
code can load straight into device arrays — with a PMML XML export shim for
ecosystem parity.

Layout on disk (a directory):
    <dir>/model.json      {"app":..., "extensions":{...}, "content":{...}}
    <dir>/tensors.npz     optional named ndarray payloads
"""

from __future__ import annotations

import base64
import io
import json
from pathlib import Path
from typing import Any, Mapping

import numpy as np

from oryx_tpu.common.ioutil import mkdirs, strip_scheme

MODEL_FILENAME = "model.json"
TENSORS_FILENAME = "tensors.npz"


class ModelArtifact:
    def __init__(
        self,
        app: str,
        extensions: Mapping[str, str] | None = None,
        content: Mapping[str, Any] | None = None,
        tensors: Mapping[str, np.ndarray] | None = None,
    ):
        self.app = app
        self.extensions: dict[str, str] = dict(extensions or {})
        self.content: dict[str, Any] = dict(content or {})
        self.tensors: dict[str, np.ndarray] = dict(tensors or {})

    # -- extensions as generic KV channel (AppPMMLUtils.getExtensionValue) --

    def get_extension(self, name: str, default: Any = None) -> Any:
        return self.extensions.get(name, default)

    def set_extension(self, name: str, value: Any) -> None:
        self.extensions[name] = value if isinstance(value, str) else json.dumps(value)

    def get_extension_list(self, name: str) -> list:
        v = self.extensions.get(name)
        if v is None:
            return []
        return json.loads(v) if isinstance(v, str) else list(v)

    # -- disk I/O (PMMLUtils.write/read) ------------------------------------

    def write(self, path: str | Path) -> Path:
        d = mkdirs(strip_scheme(str(path)))
        with open(d / MODEL_FILENAME, "w", encoding="utf-8") as f:
            json.dump(
                {"app": self.app, "extensions": self.extensions, "content": self.content},
                f,
            )
        if self.tensors:
            np.savez_compressed(d / TENSORS_FILENAME, **self.tensors)
        return d

    @staticmethod
    def read(path: str | Path) -> "ModelArtifact":
        d = Path(strip_scheme(str(path)))
        if d.is_file():
            d = d.parent
        with open(d / MODEL_FILENAME, "r", encoding="utf-8") as f:
            meta = json.load(f)
        tensors: dict[str, np.ndarray] = {}
        tp = d / TENSORS_FILENAME
        if tp.exists():
            with np.load(tp) as z:
                tensors = {k: z[k] for k in z.files}
        return ModelArtifact(meta["app"], meta.get("extensions"), meta.get("content"), tensors)

    # -- inline string form (PMMLUtils.toString/fromString) -----------------

    def to_string(self) -> str:
        doc: dict[str, Any] = {
            "app": self.app,
            "extensions": self.extensions,
            "content": self.content,
        }
        if self.tensors:
            buf = io.BytesIO()
            np.savez_compressed(buf, **self.tensors)
            doc["tensors_b64"] = base64.b64encode(buf.getvalue()).decode("ascii")
        return json.dumps(doc, separators=(",", ":"))

    @staticmethod
    def from_string(s: str) -> "ModelArtifact":
        doc = json.loads(s)
        tensors: dict[str, np.ndarray] = {}
        if "tensors_b64" in doc:
            with np.load(io.BytesIO(base64.b64decode(doc["tensors_b64"]))) as z:
                tensors = {k: z[k] for k in z.files}
        return ModelArtifact(doc["app"], doc.get("extensions"), doc.get("content"), tensors)

    # -- PMML export shim ---------------------------------------------------

    def to_pmml_xml(self) -> str:
        """Minimal PMML 4.3 document: header + extensions (+ ClusteringModel
        for k-means content), enough for external PMML consumers to read what
        the reference would have published."""
        from xml.sax.saxutils import escape, quoteattr

        lines = [
            '<?xml version="1.0" encoding="UTF-8"?>',
            '<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">',
            '  <Header><Application name="oryx_tpu"/></Header>',
        ]
        for k, v in self.extensions.items():
            lines.append(f"  <Extension name={quoteattr(k)} value={quoteattr(str(v))}/>")
        if self.app == "kmeans" and "centers" in self.tensors:
            centers = self.tensors["centers"]
            counts = self.content.get("counts", [0] * len(centers))
            n_feat = centers.shape[1] if len(centers) else 0
            lines.append(
                f'  <ClusteringModel functionName="clustering" modelClass="centerBased" '
                f'numberOfClusters="{len(centers)}">'
            )
            lines.append(
                '    <ComparisonMeasure kind="distance"><squaredEuclidean/></ComparisonMeasure>'
            )
            lines.append("    <MiningSchema/>")
            ids = self.content.get("clusterIDs") or [str(i) for i in range(len(centers))]
            for i, c in enumerate(centers):
                center = " ".join(repr(float(x)) for x in c)
                lines.append(
                    f"    <Cluster id={quoteattr(str(ids[i]))} "
                    f"size={quoteattr(str(int(counts[i])))}>"
                    f'<Array n="{n_feat}" type="real">{escape(center)}</Array></Cluster>'
                )
            lines.append("  </ClusteringModel>")
        lines.append("</PMML>")
        return "\n".join(lines)


def read_artifact_from_update(key: str, message: str) -> ModelArtifact:
    """Decode a MODEL (inline artifact) or MODEL-REF (path) update message —
    the consumer-side counterpart of the size cutover at the reference's
    MLUpdate.java:212-231 / AppPMMLUtils.readPMMLFromUpdateKeyMessage.

    MODEL-REF resolution is cross-host capable: the local path wins when it
    exists (shared mount / same host), otherwise the bus-chunked copy
    assembled by the ArtifactRelay stands in — the reference reads the
    path through a shared Hadoop FileSystem (AppPMMLUtils.java:261-275,
    FileSystem.get), which has no equivalent here without HDFS."""
    if key == "MODEL":
        # an inline MODEL is decoded by EVERY consumer that receives it —
        # inherently per-replica distribution cost (N replicas on a host
        # pay N decodes); only the chunked MODEL-REF path can amortize
        _distribution_bytes().inc(
            len(message.encode("utf-8")), mode="per-replica"
        )
        return ModelArtifact.from_string(message)
    if key == "MODEL-REF":
        return ModelArtifact.read(artifact_relay().resolve(message))
    raise ValueError(f"not a model update key: {key}")


# -- bus-chunked MODEL-REF transfer (no shared filesystem required) --------

CHUNK_KEY = "MODEL-CHUNK"

# sha marker the relay leaves beside a materialized artifact so co-hosted
# sibling processes can tell "this exact chunk stream is already decoded
# here" without re-assembling it (the fleet's amortized distribution)
RELAY_META_FILENAME = "relay.json"


def _distribution_bytes():
    """Counter behind the fleet's distribution-amortization claim:
    artifact bytes this process decoded+materialized getting a model to
    its serving replica(s). mode="shared" rode the per-host artifact
    cache (first completer decodes, siblings skip — N co-hosted replicas
    total ~1x the artifact); mode="per-replica" was a redundant
    per-process decode (inline MODELs, or sharing disabled)."""
    from oryx_tpu.common.metrics import get_registry

    return get_registry().counter(
        "oryx_fleet_distribution_bytes",
        "Artifact bytes decoded for model distribution in this process: "
        "mode=shared deduplicated through the per-host artifact cache "
        "(one decode per host), mode=per-replica redundant per-process "
        "decode (inline MODEL messages, or oryx.fleet.distribution."
        "shared=false)",
        labeled=True,
    )


class ArtifactRelay:
    """Assembles MODEL-CHUNK messages into a local artifact cache so any
    consumer on any host can resolve a MODEL-REF without a shared mount.

    The publisher emits the oversized artifact's exact serialized form as
    N b64 chunks (each under the update topic's max message size) just
    before the MODEL-REF line; replaying consumers (serving/speed read the
    update topic from earliest) re-assemble them on every restart, which
    is the same replay contract the reference relies on for UP messages.
    Requires the update topic's publish order (single partition, the
    reference's own convention for ordered model updates)."""

    # un-assembled chunks of refs OTHER than the one currently arriving;
    # the in-flight ref itself is never evicted — its transient memory
    # floor is one artifact's serialized size, the same cost the
    # publisher paid to send it
    MAX_PENDING_BYTES = 1 << 29  # 512 MB
    # materialized artifacts kept on disk per process; replay (consumers
    # read the update topic from earliest on every restart) re-walks all
    # historical models, and without a cap the cache would accrete every
    # oversized artifact ever published
    MAX_CACHED = 8

    # parked re-dispatch callbacks for refs that could not be resolved
    # when their MODEL-REF arrived (chunks still in flight, sha-mismatch
    # republish, eviction race): bounded because replay also walks
    # MODEL-REFs whose artifacts were TTL-pruned years ago and will never
    # materialize
    MAX_PARKED = 32

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        # ref -> {"n": int, "sha": str | None, "chunks": {i: bytes}}
        self._pending: dict[str, dict] = {}
        self._cache_root: Path | None = None
        # ref -> ONE re-dispatch callback (latest wins): a sha-mismatch
        # republish parks the same ref twice, and firing both would load
        # and swap the same model twice
        self._parked: dict[str, object] = {}
        # amortize assembly across co-hosted replicas (the fleet's shared
        # model distribution): a sibling's sha-marked materialization is
        # adopted instead of redundantly re-decoded. Configured from
        # oryx.fleet.distribution.shared (configure_artifact_relay).
        self.shared_distribution = True
        # cache dirs the LRU must never evict (ref -> pin count): the
        # model gate pins its adoption history so a rollback target is
        # still a local pointer swap however many generations replay
        # through the cache in between
        self._pinned: dict[str, int] = {}  # guarded-by: _lock

    def _root(self) -> Path:
        if self._cache_root is None:
            import os
            import tempfile

            # STABLE per-user root (not a fresh mkdtemp per process):
            # cache dirs are keyed by ref, so a restart's replay rewrites
            # the same paths instead of accreting a new copy of history
            # in a new directory every time
            root = Path(tempfile.gettempdir()) / (
                f"oryx-artifact-cache-{os.getuid()}"
            )
            root.mkdir(mode=0o700, parents=True, exist_ok=True)
            self._cache_root = root
        return self._cache_root

    def offer(self, message: str) -> None:
        """Ingest one MODEL-CHUNK message; materializes the artifact into
        the local cache when the last chunk arrives. With shared
        distribution on, a chunk stream a co-hosted sibling already
        assembled (matching sha marker in the shared cache) is skipped
        wholesale — not even base64-decoded — so N replicas on one host
        pay ~one decode total."""
        import hashlib

        d = json.loads(message)
        ref, i, n = str(d["ref"]), int(d["i"]), int(d["n"])
        if not (0 <= i < n):
            raise ValueError(f"bad chunk index {i}/{n}")
        if (
            self.shared_distribution
            and d.get("sha") is not None
            and self._cached_sha(ref) == d["sha"]
        ):
            # the marker re-check stays per-chunk (one tiny-file read —
            # it also notices a sibling evicting the dir mid-stream) but
            # the adoption side-effects (LRU utime, cache-root scan,
            # parked-ref fire) run once per STREAM, not once per chunk:
            # a replayed 1 GB artifact at 1 MB chunks must not cost ~1000
            # directory scans on the update-consumer thread. A parked
            # MODEL-REF fires immediately instead of waiting for the
            # stream's tail.
            with self._lock:
                self._pending.pop(ref, None)
                parked = ref in self._parked
            if parked or i == n - 1:
                self._adopt(ref)
            return
        data = base64.b64decode(d["data"])
        with self._lock:
            ent = self._pending.setdefault(
                ref, {"n": n, "sha": d.get("sha"), "chunks": {}}
            )
            if ent["n"] != n or (
                d.get("sha") is not None and d["sha"] != ent["sha"]
            ):
                # a republish changed the chunking OR the bytes (same
                # count, new content after a publisher restart): restart
                # the assembly — mixing streams would fail verification
                # forever
                ent = self._pending[ref] = {
                    "n": n, "sha": d.get("sha"), "chunks": {}
                }
            ent["chunks"][i] = data
            self._evict_locked(keep=ref)
            if len(ent["chunks"]) < n:
                return
            blob = b"".join(ent["chunks"][j] for j in range(n))
            del self._pending[ref]
        sha = ent.get("sha")
        if sha and hashlib.sha256(blob).hexdigest() != sha:
            raise ValueError(f"MODEL-CHUNK sha mismatch for {ref}")
        self._finish(ref, blob, sha)

    def _finish(self, ref: str, blob: bytes, sha: str | None) -> None:
        """Decode + materialize one fully assembled chunk stream, deduped
        across co-hosted processes when sharing is on: the assembly lock
        serializes the (fast) decode+write, and a loser re-checking the
        sha marker under the lock adopts the winner's bytes-identical
        copy instead of decoding its own. Either way exactly one process
        counts the blob into mode=shared; the disabled path counts every
        process's decode into mode=per-replica."""
        if self.shared_distribution and sha is not None:
            with self._assembly_lock(ref):
                if self._cached_sha(ref) == sha:
                    # lost the race to a sibling replica — its copy is the
                    # same bytes (same sha); nothing left to decode
                    self._adopt(ref)
                    return
                art = ModelArtifact.from_string(blob.decode("utf-8"))
                self._materialize(ref, art, sha=sha)
            _distribution_bytes().inc(len(blob), mode="shared")
            return
        art = ModelArtifact.from_string(blob.decode("utf-8"))
        self._materialize(ref, art, sha=sha)
        _distribution_bytes().inc(len(blob), mode="per-replica")

    def _adopt(self, ref: str) -> None:
        """Adopt a sibling's materialization as this relay's own: bump the
        shared LRU stamp and apply this relay's cache cap. An adopting
        consumer replaying a long topic history must prune exactly like a
        materializing one would, or its MAX_CACHED stops bounding the
        shared root whenever the artifacts are already decoded."""
        import os

        dest = self._dest(ref)
        try:
            os.utime(dest)
        except OSError:
            pass
        self._evict_cache_dirs(keep=dest)
        self._fire_parked(ref)

    def _assembly_lock(self, ref: str):
        """Cross-process exclusive lock for one ref's decode+materialize
        (an advisory flock file beside the cache dir — a dotfile, so the
        cache-dir LRU never sees it). Platforms without fcntl fall back
        to unlocked operation: the race then just costs a redundant
        decode, never corruption (materialize is rename-atomic)."""
        from contextlib import contextmanager

        @contextmanager
        def _cm():
            try:
                import fcntl

                f = open(self._root() / f".{self._dest(ref).name}.lock", "a+b")
            except (ImportError, OSError):
                yield
                return
            try:
                fcntl.flock(f, fcntl.LOCK_EX)
                yield
            finally:
                try:
                    fcntl.flock(f, fcntl.LOCK_UN)
                except OSError:  # pragma: no cover - unlock-on-close wins
                    pass
                f.close()

        return _cm()

    def _cached_sha(self, ref: str) -> str | None:
        """sha of the materialized artifact for `ref` in the shared cache,
        or None (not materialized, or materialized by a pre-marker
        writer)."""
        try:
            with open(
                self._dest(ref) / RELAY_META_FILENAME, encoding="utf-8"
            ) as f:
                v = json.load(f).get("sha")
            return str(v) if v else None
        except (OSError, ValueError):
            return None

    def _dest(self, ref: str) -> Path:
        """The deterministic cache dir for a ref — derived, not tracked:
        every process sharing the root computes the same path, so one
        process's materialization is a cache hit for its siblings."""
        import hashlib

        return self._root() / hashlib.sha256(ref.encode()).hexdigest()[:24]

    def _materialize(
        self, ref: str, art: ModelArtifact, sha: str | None = None
    ) -> None:
        """Write the assembled artifact into the stable cache, atomically
        enough for concurrent processes: build in a per-pid temp dir, then
        rename into place; a lost race just adopts the winner's copy
        (identical bytes — both assembled the same chunk stream). The sha
        marker rides INSIDE the dir (written before the rename) so a
        sibling never reads a marker whose artifact is half-written."""
        import os
        import shutil

        dest = self._dest(ref)
        tmp = self._root() / f".{dest.name}.tmp-{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        art.write(tmp)
        if sha is not None:
            with open(tmp / RELAY_META_FILENAME, "w", encoding="utf-8") as f:
                json.dump({"sha": sha}, f)
        shutil.rmtree(dest, ignore_errors=True)
        try:
            os.replace(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)  # another process won
        try:
            os.utime(dest)  # shared LRU stamp (see _evict_cache_dirs)
        except OSError:
            pass
        self._evict_cache_dirs(keep=dest)
        self._fire_parked(ref)

    def _evict_cache_dirs(self, keep: Path) -> None:
        """Cross-PROCESS LRU over the shared per-user cache root: speed
        and serving consumers on one host share the root, so eviction
        must rank by shared state — directory mtimes, bumped on every
        materialize and resolve — not a per-process dict. (Round-4
        advice: per-process LRU deleted dirs a sibling process still held
        in its in-memory cache, silently dropping its MODEL update.) A
        dir in active use carries a fresh stamp and survives; any
        residual race is caught by resolve()'s existence re-check."""
        import shutil

        try:
            dirs = [
                d
                for d in self._root().iterdir()
                if d.is_dir() and not d.name.startswith(".")
            ]
        except OSError:
            return
        if len(dirs) <= self.MAX_CACHED:
            return

        def mtime(d: Path) -> float:
            try:
                return d.stat().st_mtime
            except OSError:  # concurrently evicted by a sibling
                return 0.0

        with self._lock:
            pinned = {self._dest(r).name for r in self._pinned}
        dirs.sort(key=mtime)
        for d in dirs[: len(dirs) - self.MAX_CACHED]:
            if d != keep and d.name not in pinned:
                shutil.rmtree(d, ignore_errors=True)

    def _evict_locked(self, keep: str) -> None:
        total = sum(
            len(c)
            for e in self._pending.values()
            for c in e["chunks"].values()
        )
        while total > self.MAX_PENDING_BYTES:
            victim = next(
                (r for r in self._pending if r != keep), None
            )
            if victim is None:
                return  # never evict the ref currently being assembled
            ev = self._pending.pop(victim)
            total -= sum(len(c) for c in ev["chunks"].values())
            import logging

            logging.getLogger(__name__).warning(
                "artifact relay evicted pending chunks for %s", victim
            )

    def park(self, ref: str, redispatch) -> None:
        """Register a callback to re-run when `ref` later materializes —
        the dispatch loop's short OSError retries give up in ~1.2s, which
        loses the model permanently when the chunk stream simply hadn't
        finished (multi-partition lag, sha-mismatch republish, eviction
        race). One callback per ref, latest wins: a republished ref parks
        twice but must dispatch once. Parked callbacks fire from
        _materialize; the register-then-recheck order closes the race
        against a materialization landing between the caller's last retry
        and the park."""
        import logging

        with self._lock:
            self._parked[ref] = redispatch
            while len(self._parked) > self.MAX_PARKED:
                victim = next(r for r in self._parked if r != ref)
                del self._parked[victim]
                logging.getLogger(__name__).warning(
                    "dropping parked MODEL-REF %s (parking full)", victim
                )
        try:
            self.resolve(ref)
        except OSError:
            return  # genuinely pending: _materialize will fire it
        self._fire_parked(ref)

    def _fire_parked(self, ref: str) -> None:
        import logging

        with self._lock:
            cb = self._parked.pop(ref, None)
        if cb is not None:
            try:
                cb()
            except Exception:
                logging.getLogger(__name__).exception(
                    "parked MODEL-REF re-dispatch failed for %s", ref
                )

    def pin(self, ref: str) -> None:
        """Exempt a ref's cache dir from LRU eviction (refcounted — the
        model gate pins every adoption-history entry and a generation can
        re-enter history). Pinning is advisory: it protects the CACHE
        copy only, and a ref resolving through its original path needs no
        protection at all."""
        import os

        with self._lock:
            self._pinned[ref] = self._pinned.get(ref, 0) + 1
        try:
            os.utime(self._dest(ref))
        except OSError:
            pass  # not materialized here (original path, inline MODEL)

    def unpin(self, ref: str) -> None:
        """Drop one pin on a ref; at zero the dir rejoins the normal LRU
        (not deleted eagerly — it may be the freshest entry)."""
        with self._lock:
            n = self._pinned.get(ref, 0) - 1
            if n <= 0:
                self._pinned.pop(ref, None)
            else:
                self._pinned[ref] = n

    def resolve(self, ref: str) -> str:
        """A readable local path for a MODEL-REF: the path itself when it
        exists, else the bus-assembled cache copy. FileNotFoundError (an
        OSError — the dispatch loop's transient-I/O retry class) when
        neither is available yet."""
        p = Path(strip_scheme(ref))
        if (p / MODEL_FILENAME).exists() or p.is_file():
            return str(p)
        c = self._dest(ref)  # derived path: a SIBLING process's
        if (c / MODEL_FILENAME).exists():  # materialization is a hit too
            import os

            try:
                os.utime(c)  # shared LRU stamp: in-use dirs survive
            except OSError:
                pass
            return str(c)
        raise FileNotFoundError(
            f"MODEL-REF {ref} is not readable locally and no complete "
            f"bus-chunked copy has arrived"
        )


_RELAY: ArtifactRelay | None = None


def artifact_relay() -> ArtifactRelay:
    """Process-global relay: one consumer-side cache shared by every
    listener thread in the process."""
    global _RELAY
    if _RELAY is None:
        _RELAY = ArtifactRelay()
    return _RELAY


def configure_artifact_relay(config) -> None:
    """Adopt the fleet's distribution mode (called wherever a process
    adopts its config — ServingApp, layer startup): shared = amortize
    chunk assembly across co-hosted replicas through the per-host cache;
    off restores strictly per-process decodes."""
    artifact_relay().shared_distribution = config.get_bool(
        "oryx.fleet.distribution.shared", True
    )


def publish_model_ref(
    producer,
    serialized: str,
    model_path: str,
    max_message_size: int,
    transfer: bool = True,
) -> None:
    """Publish an oversized model as MODEL-CHUNK x N + MODEL-REF. transfer
    False restores the reference's bare-path behavior (shared-mount
    deployments that don't want the topic to carry the artifact bytes)."""
    # headroom for the JSON envelope (ref path + indices + sha), then 4/3
    # b64 expansion; a cap too small to carry even the envelope falls back
    # to the bare reference (chunks would overrun the topic's limit)
    budget = (max_message_size - 512 - len(model_path)) // 4 * 3
    if transfer and budget < 1:
        import logging

        logging.getLogger(__name__).warning(
            "update-topic max-size %d too small for artifact chunks; "
            "publishing bare MODEL-REF (consumers need path access)",
            max_message_size,
        )
        transfer = False
    if transfer:
        import hashlib
        import math

        raw = serialized.encode("utf-8")
        sha = hashlib.sha256(raw).hexdigest()
        n = max(1, math.ceil(len(raw) / budget))
        # chunks ship in bounded batches through send_batch (one broker
        # lock round-trip per group instead of per chunk; same-key records
        # share a partition, so publish order is preserved). The group cap
        # bounds transient memory to ~8 encoded chunks, not the whole
        # artifact twice.
        send_batch = getattr(producer, "send_batch", None)
        batch: list[tuple[str, str]] = []

        def _flush() -> None:
            if not batch:
                return
            if send_batch is not None:
                send_batch(batch)
            else:  # bare-broker callers without the batch API
                for key, msg in batch:
                    producer.send(key, msg)
            batch.clear()

        for i in range(n):
            batch.append(
                (
                    CHUNK_KEY,
                    json.dumps(
                        {
                            "ref": model_path,
                            "i": i,
                            "n": n,
                            "sha": sha,
                            "data": base64.b64encode(
                                raw[i * budget : (i + 1) * budget]
                            ).decode("ascii"),
                        },
                        separators=(",", ":"),
                    ),
                )
            )
            if len(batch) >= 8:
                _flush()
        _flush()
    producer.send("MODEL-REF", model_path)
