"""Mesh construction and sharding utilities.

Design: one logical mesh with axes ("data", "model"). The training kernels
shard their leading entity dimension (users / points / trees) over "data"
and keep factor/centroid tables replicated or sharded over "model"; XLA
inserts the collectives (psum for Gram matrices, all_gather for factor
reads) that the reference implemented as Spark shuffles and partition-sum
fan-ins (e.g. the parallel VTV sum in PartitionedFeatureVectors.java:209-213
is literally the psum XLA derives from a sharded X^T.X einsum).

Multi-host: when jax.distributed is initialized, jax.devices() spans all
hosts and the same mesh-building code scales out over DCN; nothing here is
single-host-specific.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; -1 means 'all remaining devices'."""

    data: int = -1
    model: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int]:
        model = self.model if self.model > 0 else 1
        data = self.data if self.data > 0 else max(1, n_devices // model)
        if data * model > n_devices:
            raise ValueError(
                f"mesh {data}x{model} needs {data * model} devices, have {n_devices}"
            )
        return data, model


def make_mesh(spec: MeshSpec | None = None, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec()
    data, model = spec.resolve(len(devices))
    dev_array = np.asarray(devices[: data * model]).reshape(data, model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def host_mesh(n: int | None = None) -> Mesh:
    """Flat data-parallel mesh over the first n (default all) devices."""
    devices = jax.devices()
    n = n or len(devices)
    return make_mesh(MeshSpec(data=n, model=1), devices[:n])


def model_mesh(n: int | None = None) -> Mesh:
    """Mesh whose MODEL axis spans the first n (default all) devices —
    the layout the sharded factor tables (ops/shard_topk.py, the pjit
    bucketed trainer) shard their row dimension over. On CPU test hosts
    the conftest's virtual 8-device mesh makes model_mesh(n) a faithful
    n-shard simulation."""
    devices = jax.devices()
    n = n or len(devices)
    return make_mesh(MeshSpec(data=1, model=n), devices[:n])


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading dim over "data", replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def model_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading dim over "model", replicate the rest — the
    factor-table layout of the sharded trainer (rows split across the
    model axis, every other operand replicated)."""
    return NamedSharding(mesh, P(MODEL_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_array(x, mesh: Mesh, leading: bool = True):
    """Place an array on the mesh, sharding the leading dim over "data"
    (padding it to a multiple of the axis size) or fully replicated."""
    import jax.numpy as jnp

    x = jnp.asarray(x)
    if not leading or x.ndim == 0:
        return jax.device_put(x, replicated(mesh))
    n = mesh.shape[DATA_AXIS]
    rem = x.shape[0] % n
    if rem:
        pad = [(0, n - rem)] + [(0, 0)] * (x.ndim - 1)
        x = jnp.pad(x, pad)
    return jax.device_put(x, data_sharding(mesh, x.ndim))


def pad_to_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def pcast_varying_compat(x, axes: tuple[str, ...]):
    """jax.lax.pcast(x, axes, to="varying") where the running jax has
    VMA typing (0.6+); identity elsewhere — the experimental shard_map
    of older versions has no varying-manual-axes type to cast into, and
    a replicated carry is accepted as-is."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axes, to="varying")


def shard_map_compat(body, *, mesh: Mesh, in_specs, out_specs, **kw):
    """jax.shard_map across the jax versions this repo meets: the public
    `jax.shard_map` (0.6+) when it exists, else the experimental form —
    whose replication-check kwarg is spelled `check_rep`, not
    `check_vma`. One shim so every shard_map program in the tree (TP
    trainer, ring/Ulysses attention, the sharded-serve collective) runs
    on both."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm  # type: ignore

        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
    return sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
