"""Wordcount example-app test: the minimal custom-SPI path (reference
app/example + its ITs) end-to-end through real layers — batch publishes a
JSON MODEL, speed emits per-batch "word,count" deltas, serving applies
both and answers /distinct over HTTP, all classes loaded reflectively
from config like the reference's config-named plugin points."""

import json
import time
import urllib.error
import urllib.request

import pytest

from oryx_tpu.apps.example.batch import (
    ExampleBatchLayerUpdate,
    count_distinct_other_words,
)
from oryx_tpu.apps.example.speed import ExampleSpeedModelManager
from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.bus.broker import get_broker, topics
from oryx_tpu.bus.inproc import InProcBroker
from oryx_tpu.common.config import load_config
from oryx_tpu.common.ioutil import choose_free_port
from oryx_tpu.layers import BatchLayer, SpeedLayer
from oryx_tpu.serving.server import ServingLayer


@pytest.fixture(autouse=True)
def _fresh():
    InProcBroker.reset_all()
    yield
    InProcBroker.reset_all()


def _cfg(tmp_path, port=0):
    return load_config(overlay={
        "oryx.id": "wc",
        "oryx.input-topic.broker": "mem://wc",
        "oryx.update-topic.broker": "mem://wc",
        "oryx.batch.storage.data-dir": str(tmp_path / "data"),
        "oryx.batch.storage.model-dir": str(tmp_path / "model"),
        "oryx.serving.api.port": port,
        "oryx.batch.update-class":
            "oryx_tpu.apps.example.batch.ExampleBatchLayerUpdate",
        "oryx.speed.model-manager-class":
            "oryx_tpu.apps.example.speed.ExampleSpeedModelManager",
        "oryx.serving.model-manager-class":
            "oryx_tpu.apps.example.serving.ExampleServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.example",
        ],
    })


def test_count_distinct_other_words():
    counts = count_distinct_other_words(["a b c", "a b", "a a"])
    # a co-occurs with b and c; b with a and c; c with a and b
    assert counts == {"a": 2, "b": 2, "c": 2}
    assert count_distinct_other_words(["solo"]) == {}


def test_speed_manager_accumulates_deltas():
    mgr = ExampleSpeedModelManager()
    mgr.consume_key_message("MODEL", json.dumps({"a": 5}))
    ups = set(mgr.build_updates([KeyMessage(None, "a b")]))
    assert ups == {("UP", "a,6"), ("UP", "b,1")}
    mgr.consume_key_message("UP", "a,6")  # ignored
    assert set(mgr.build_updates([KeyMessage(None, "a c")])) == {("UP", "a,7"), ("UP", "c,1")}


def _http(method, url, body=None):
    req = urllib.request.Request(
        url, method=method, data=body, headers={"Accept": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_wordcount_end_to_end(tmp_path):
    port = choose_free_port()
    cfg = _cfg(tmp_path, port)
    topics.maybe_create("mem://wc", "OryxInput", 1)
    topics.maybe_create("mem://wc", "OryxUpdate", 1)
    broker = get_broker("mem://wc")

    serving = ServingLayer(cfg)  # manager loaded from config by class name
    serving.start()
    base = f"http://127.0.0.1:{port}"

    # ingest lines via REST
    status, _ = _http("POST", f"{base}/add", b"cat dog\ncat fish\n")
    assert status == 200

    # batch generation: loads update class reflectively, publishes MODEL
    batch = BatchLayer(cfg)
    assert isinstance(batch.update, ExampleBatchLayerUpdate)
    batch.ensure_streams()
    batch._consumer._fetch_pos = {p: 0 for p in batch._consumer._fetch_pos}
    n = batch.run_generation(timestamp_ms=1_700_000_000_000)
    assert n == 2
    batch.close()
    recs = broker.read("OryxUpdate", 0, 0, 10)
    assert recs and recs[0][1] == "MODEL"
    assert json.loads(recs[0][2]) == {"cat": 2, "dog": 1, "fish": 1}

    # serving replays the update topic and answers /distinct
    deadline = time.time() + 20
    while time.time() < deadline:
        status, body = _http("GET", f"{base}/distinct/cat")
        if status == 200:
            break
        time.sleep(0.1)
    assert status == 200 and json.loads(body) == 2
    status, body = _http("GET", f"{base}/distinct")
    assert status == 200 and json.loads(body) == {"cat": 2, "dog": 1, "fish": 1}
    status, _ = _http("GET", f"{base}/distinct/nope")
    assert status == 400

    # speed layer: consumes the MODEL, emits deltas for a new micro-batch
    speed = SpeedLayer(cfg)
    speed.ensure_streams()
    speed.start()
    deadline = time.time() + 20
    while time.time() < deadline:
        if speed.manager._words:
            break
        time.sleep(0.1)
    assert speed.manager._words.get("cat") == 2
    ups = speed.manager.build_updates([KeyMessage(None, "cat bird")])
    assert set(ups) == {("UP", "cat,3"), ("UP", "bird,1")}
    for key, u in ups:
        broker.send("OryxUpdate", key, u)
    deadline = time.time() + 20
    while time.time() < deadline:
        status, body = _http("GET", f"{base}/distinct/bird")
        if status == 200:
            break
        time.sleep(0.1)
    assert status == 200 and json.loads(body) == 1
    speed.close()
    serving.close()
