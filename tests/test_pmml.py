"""PMML import: reference-style documents -> artifacts + host evaluation.
Covers the three model families the reference publishes (ALS skeleton with
extensions, k-means ClusteringModel, RDF MiningModel of TreeModels) and the
export/import round-trip for the native k-means artifact."""

from __future__ import annotations

import numpy as np
import pytest

from oryx_tpu.common.artifact import ModelArtifact
from oryx_tpu.common.pmml import PredicateForest, pmml_to_artifact

ALS_SKELETON = """<?xml version="1.0" encoding="UTF-8"?>
<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header><Application name="Oryx"/></Header>
  <Extension name="X" value="als/X/"/>
  <Extension name="Y" value="als/Y/"/>
  <Extension name="features" value="10"/>
  <Extension name="implicit" value="true"/>
  <Extension name="XIDs">u1 u2 u3</Extension>
  <Extension name="YIDs">i1 i2</Extension>
</PMML>"""

KMEANS_PMML = """<?xml version="1.0" encoding="UTF-8"?>
<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <ClusteringModel functionName="clustering" modelClass="centerBased" numberOfClusters="2">
    <ComparisonMeasure kind="distance"><squaredEuclidean/></ComparisonMeasure>
    <MiningSchema/>
    <Cluster id="0" size="5"><Array n="2" type="real">1.0 2.0</Array></Cluster>
    <Cluster id="1" size="7"><Array n="2" type="real">-1.5 0.5</Array></Cluster>
  </ClusteringModel>
</PMML>"""

# reference-shaped forest: numeric greaterThan split (positive child) with
# an isNotIn categorical split below, score distributions at the leaves
RDF_PMML = """<?xml version="1.0" encoding="UTF-8"?>
<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <MiningModel functionName="classification">
    <MiningSchema/>
    <Segmentation multipleModelMethod="weightedMajorityVote">
      <Segment weight="1.0">
        <True/>
        <TreeModel functionName="classification">
          <MiningSchema/>
          <Node id="r">
            <True/>
            <Node id="r+" score="yes" recordCount="10">
              <SimplePredicate field="age" operator="greaterThan" value="30"/>
              <ScoreDistribution value="yes" recordCount="8"/>
              <ScoreDistribution value="no" recordCount="2"/>
            </Node>
            <Node id="r-">
              <SimpleSetPredicate field="color" booleanOperator="isNotIn">
                <Array n="2" type="string">red blue</Array>
              </SimpleSetPredicate>
              <Node id="r-+" score="no" recordCount="4">
                <SimplePredicate field="age" operator="lessOrEqual" value="10"/>
                <ScoreDistribution value="no" recordCount="4"/>
              </Node>
              <Node id="r--" score="yes" recordCount="6">
                <True/>
                <ScoreDistribution value="yes" recordCount="5"/>
                <ScoreDistribution value="no" recordCount="1"/>
              </Node>
            </Node>
          </Node>
        </TreeModel>
      </Segment>
      <Segment weight="2.0">
        <True/>
        <TreeModel functionName="classification">
          <MiningSchema/>
          <Node id="r" score="no" recordCount="20">
            <True/>
            <ScoreDistribution value="no" recordCount="15"/>
            <ScoreDistribution value="yes" recordCount="5"/>
          </Node>
        </TreeModel>
      </Segment>
    </Segmentation>
  </MiningModel>
</PMML>"""


def test_als_skeleton_import():
    art = pmml_to_artifact(ALS_SKELETON)
    assert art.app == "als"
    assert art.extensions["features"] == "10"
    assert art.extensions["X"] == "als/X/"
    assert art.extensions["XIDs"] == ["u1", "u2", "u3"]
    assert art.extensions["YIDs"] == ["i1", "i2"]


def test_kmeans_import():
    art = pmml_to_artifact(KMEANS_PMML)
    assert art.app == "kmeans"
    np.testing.assert_allclose(
        art.tensors["centers"], [[1.0, 2.0], [-1.5, 0.5]]
    )
    assert art.content["counts"] == [5, 7]


def test_kmeans_export_import_round_trip():
    art = ModelArtifact(
        "kmeans", tensors={"centers": np.asarray([[0.5, -1.0], [2.0, 3.0]], np.float32)}
    )
    art.content["counts"] = [3, 9]
    back = pmml_to_artifact(art.to_pmml_xml())
    np.testing.assert_allclose(back.tensors["centers"], art.tensors["centers"])
    assert back.content["counts"] == [3, 9]


def test_rdf_import_and_predict():
    art = pmml_to_artifact(RDF_PMML)
    assert art.app == "rdf-pmml"
    forest = PredicateForest.from_artifact(art)
    assert forest.is_classification and len(forest.trees) == 2

    # age>30: tree1 leaf r+ dist {yes:.8,no:.2}; tree2 (w=2) {no:.75,yes:.25}
    label, dist = forest.predict({"age": 40, "color": "red"})
    expect_yes = 1.0 * 0.8 + 2.0 * 0.25
    expect_no = 1.0 * 0.2 + 2.0 * 0.75
    assert label == "no"
    np.testing.assert_allclose(dist["no"], expect_no / (expect_yes + expect_no))

    # age<=10 and color not in {red, blue}: tree1 -> r-+ (no)
    label, dist = forest.predict({"age": 5, "color": "green"})
    assert label == "no"

    # age in (10, 30], color green -> r-- leaf {yes: 5/6}
    label, dist = forest.predict({"age": 20, "color": "green"})
    expect_yes = 1.0 * (5 / 6) + 2.0 * 0.25
    expect_no = 1.0 * (1 / 6) + 2.0 * 0.75
    assert dist["yes"] == pytest.approx(expect_yes / (expect_yes + expect_no))


def test_rdf_regression_weighted_average():
    xml = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3"><Header/>
    <MiningModel functionName="regression"><MiningSchema/>
      <Segmentation multipleModelMethod="weightedAverage">
        <Segment weight="1.0"><True/>
          <TreeModel functionName="regression"><MiningSchema/>
            <Node id="r" score="10.0"><True/></Node>
          </TreeModel></Segment>
        <Segment weight="3.0"><True/>
          <TreeModel functionName="regression"><MiningSchema/>
            <Node id="r" score="20.0"><True/></Node>
          </TreeModel></Segment>
      </Segmentation>
    </MiningModel></PMML>"""
    forest = PredicateForest.from_artifact(pmml_to_artifact(xml))
    assert forest.predict({}) == pytest.approx((10.0 + 3 * 20.0) / 4.0)


def test_single_tree_model_import():
    xml = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3"><Header/>
    <TreeModel functionName="classification"><MiningSchema/>
      <Node id="r" score="a"><True/></Node>
    </TreeModel></PMML>"""
    art = pmml_to_artifact(xml)
    assert art.app == "rdf-pmml" and len(art.content["trees"]) == 1
    label, _ = PredicateForest.from_artifact(art).predict({})
    assert label == "a"


def test_quoted_string_array_values():
    xml = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3"><Header/>
    <TreeModel functionName="classification"><MiningSchema/>
      <Node id="r">
        <True/>
        <Node id="r+" score="hit">
          <SimpleSetPredicate field="c" booleanOperator="isIn">
            <Array n="2" type="string">"new york" boston</Array>
          </SimpleSetPredicate>
        </Node>
        <Node id="r-" score="miss"><True/></Node>
      </Node>
    </TreeModel></PMML>"""
    forest = PredicateForest.from_artifact(pmml_to_artifact(xml))
    assert forest.predict({"c": "new york"})[0] == "hit"
    assert forest.predict({"c": "chicago"})[0] == "miss"


def test_cli_import_pmml_feeds_running_serving_model(tmp_path):
    """Migration path end-to-end: reference k-means PMML -> import-pmml CLI
    -> update topic -> the k-means serving manager loads it."""
    from oryx_tpu import cli
    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.common.config import load_config
    from oryx_tpu.apps.kmeans.serving import KMeansServingModelManager

    pmml_file = tmp_path / "model.pmml.xml"
    pmml_file.write_text(KMEANS_PMML)
    sets = [
        "oryx.input-topic.broker=mem://pmmlcli",
        "oryx.update-topic.broker=mem://pmmlcli",
    ]
    flags = [x for s in sets for x in ("--set", s)]
    assert cli.main(["setup", *flags]) == 0
    assert cli.main(["import-pmml", "--pmml", str(pmml_file), *flags]) == 0

    broker = get_broker("mem://pmmlcli")
    recs = broker.read("OryxUpdate", 0, 0, 10)
    assert recs and recs[-1][1] == "MODEL"

    cfg = load_config(overlay={
        "oryx.input-topic.broker": "mem://pmmlcli",
        "oryx.update-topic.broker": "mem://pmmlcli",
        "oryx.input-schema.num-features": 2,
        "oryx.input-schema.numeric-features": ["0", "1"],
    })
    manager = KMeansServingModelManager(cfg)
    manager.consume_key_message("MODEL", recs[-1][2])
    model = manager.get_model()
    assert model is not None
    # point near the second imported center assigns to cluster 1
    assert model.closest_cluster(np.asarray([-1.4, 0.4]))[0] == 1


def test_rdf_serving_manager_consumes_imported_forest():
    """Imported PMML forest must actually serve: MODEL -> predict ->
    live UP node update shifts the distribution (node ids are the
    reference's own path strings)."""
    from oryx_tpu.apps.rdf.serving import PMMLForestServingModel, RDFServingModelManager
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.pmml import pmml_to_artifact
    import json

    cfg = load_config(overlay={
        "oryx.input-topic.broker": "mem://pmmlrdf",
        "oryx.update-topic.broker": "mem://pmmlrdf",
        "oryx.input-schema.feature-names": ["age", "color", "label"],
        "oryx.input-schema.numeric-features": ["age"],
        "oryx.input-schema.categorical-features": ["color", "label"],
        "oryx.input-schema.target-feature": "label",
    })
    manager = RDFServingModelManager(cfg)
    art = pmml_to_artifact(RDF_PMML)
    manager.consume_key_message("MODEL", art.to_string())
    model = manager.get_model()
    assert isinstance(model, PMMLForestServingModel)
    label, dist = model.predict("40,red,")
    assert label == "no" and set(dist) == {"yes", "no"}
    assert model.classification_distribution("40,red,")["no"] == pytest.approx(
        dist["no"]
    )
    # live update: flood tree 0 leaf r+ with 'yes' counts -> yes share rises
    before = dist["yes"]
    manager.consume_key_message("UP", json.dumps([0, "r+", {"yes": 1000}]))
    _, dist2 = model.predict("40,red,")
    assert dist2["yes"] > before


def test_unsupported_predicate_rejected_at_import():
    xml = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3"><Header/>
    <TreeModel functionName="classification"><MiningSchema/>
      <Node id="r">
        <True/>
        <Node id="r+" score="a">
          <CompoundPredicate booleanOperator="and">
            <SimplePredicate field="x" operator="greaterThan" value="1"/>
            <SimplePredicate field="x" operator="lessThan" value="5"/>
          </CompoundPredicate>
        </Node>
        <Node id="r-" score="b"><True/></Node>
      </Node>
    </TreeModel></PMML>"""
    with pytest.raises(ValueError, match="CompoundPredicate"):
        pmml_to_artifact(xml)


def _rdf_schema_cfg(bus: str):
    from oryx_tpu.common.config import load_config

    return load_config(overlay={
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.input-schema.feature-names": ["age", "color", "label"],
        "oryx.input-schema.numeric-features": ["age"],
        "oryx.input-schema.categorical-features": ["color", "label"],
        "oryx.input-schema.target-feature": "label",
    })


def test_speed_manager_consumes_imported_forest_and_emits_label_counts():
    """The whole migration loop: speed tier loads the imported forest,
    routes a micro-batch by predicate, emits label-keyed (tree, node)
    stats, and the serving tier folds them."""
    import json
    from oryx_tpu.apps.rdf.speed import RDFSpeedModelManager
    from oryx_tpu.apps.rdf.serving import RDFServingModelManager
    from oryx_tpu.common.pmml import pmml_to_artifact

    class KM:
        def __init__(self, message):
            self.key, self.message = None, message

    art = pmml_to_artifact(RDF_PMML)
    speed = RDFSpeedModelManager(_rdf_schema_cfg("mem://pmmlspeed"))
    speed.consume_key_message("MODEL", art.to_string())
    assert speed.pmml_forest is not None

    updates = speed.build_updates(
        [KM("40,red,yes"), KM("45,blue,yes"), KM("40,red,no"), KM("5,green,no")]
    )
    assert all(key == "UP" for key, _ in updates)
    parsed = [json.loads(u) for _, u in updates]
    # both age>30 examples land in tree-0 node r+; labels are strings
    by_node = {(t, n): counts for t, n, counts in parsed}
    assert by_node[(0, "r+")] == {"yes": 2, "no": 1}
    assert by_node[(0, "r-+")] == {"no": 1}

    serving = RDFServingModelManager(_rdf_schema_cfg("mem://pmmlspeed"))
    serving.consume_key_message("MODEL", art.to_string())
    before = serving.get_model().predict("40,red,")[1]["yes"]
    for _, u in updates:
        serving.consume_key_message("UP", u)
    after = serving.get_model().predict("40,red,")[1]["yes"]
    assert after != before  # folded


def test_missing_feature_descends_default_branch():
    """A datum whose split feature is empty must still reach a leaf (the
    reference's evaluator always descends; last child = negative branch)."""
    from oryx_tpu.common.pmml import PredicateForest

    forest = PredicateForest.from_artifact(pmml_to_artifact(RDF_PMML))
    # age missing: root's children (greaterThan / isNotIn with color red)
    # -> falls to r- subtree, then age<=10 false -> default r--
    label, dist = forest.predict({"color": "red"})
    assert label in ("yes", "no") and dist


def test_import_pmml_oversized_model_uses_model_ref(tmp_path):
    from oryx_tpu import cli
    from oryx_tpu.bus.broker import get_broker
    from oryx_tpu.common.artifact import read_artifact_from_update

    pmml_file = tmp_path / "model.pmml.xml"
    pmml_file.write_text(KMEANS_PMML)
    sets = [
        "oryx.input-topic.broker=mem://pmmlref",
        "oryx.update-topic.broker=mem://pmmlref",
        f"oryx.batch.storage.model-dir={tmp_path}/models",
        "oryx.update-topic.message.max-size=64",  # force the REF path
    ]
    flags = [x for s in sets for x in ("--set", s)]
    assert cli.main(["setup", *flags]) == 0
    assert cli.main(["import-pmml", "--pmml", str(pmml_file), *flags]) == 0
    recs = get_broker("mem://pmmlref").read("OryxUpdate", 0, 0, 10)
    key, message = recs[-1][1], recs[-1][2]
    assert key == "MODEL-REF"
    art = read_artifact_from_update(key, message)
    assert art.app == "kmeans"
    np.testing.assert_allclose(art.tensors["centers"][0], [1.0, 2.0])


def test_rejects_non_pmml():
    with pytest.raises(ValueError):
        pmml_to_artifact("<NotPMML/>")
    with pytest.raises(ValueError):
        pmml_to_artifact('<PMML xmlns="http://www.dmg.org/PMML-4_3"><Header/></PMML>')
