"""Runtime device-performance accounting: per-dispatch cost records,
live MFU, occupancy, and on-demand profile windows.

Until now MFU and FLOP accounting lived only inside bench.py — a
production process could be running at 0.9% MFU (the measured TPU
serving figure) with nothing on /metrics saying so. This module promotes
that accounting from bench-time to runtime: every device dispatch — a
coalesced top-k group in the serving batcher, a train-scan chunk in the
ALS builder — reports its analytic FLOPs (ops/flops.py), bytes moved,
wall-clock, and padding occupancy into a process-wide ring of
``DispatchRecord``s, from which live gauges/histograms are derived:

- ``oryx_device_mfu{kind}`` — achieved FLOP/s over the chip's dense
  peak, computed over a rolling window (``oryx.monitoring.perf.
  window-sec``). Zeroed for the fallback window after any device→host
  failover, so degraded host throughput is never mistaken for healthy
  device throughput. NaN when no peak is known (off-TPU) and no
  ``assumed-peak-flops`` override is configured — an unknown peak must
  not render as a confident 0.
- ``oryx_device_flops_per_sec{kind}`` — the achieved numerator alone,
  meaningful even where no honest peak exists (CPU).
- ``oryx_device_dispatch_seconds{kind}`` — per-dispatch wall-clock
  (exponential buckets; carries metric→trace exemplars when tracing is
  enabled).
- ``oryx_dispatch_batch_occupancy{kind}`` — valid rows / capacity-padded
  rows of the scored view (linear buckets): the padding waste of the
  serving capacity ladder (PR 3) and the train-scan row padding, finally
  visible in production. Always <= 1.0.
- ``oryx_device_bytes_per_dispatch{kind}`` — approximate bytes the
  dispatch moved (operand streams + host transfers).
- ``oryx_device_fallback_dispatches_total`` — host-fallback scoring
  dispatches (one per request scored on the host after a device error or
  wedge failover).

The record path is cheap (a handful of float ops + bounded-ring append +
histogram observes) and always on — unlike tracing there is no off
switch to forget; the disabled cost a switch would save is already near
zero. ``/debug/profile`` (serving/resources/common.py) captures an
on-demand window of these records — optionally alongside a
``jax.profiler`` device trace — as a Perfetto-loadable artifact.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from oryx_tpu.common.metrics import (
    exponential_buckets,
    get_registry,
    linear_buckets,
)
from oryx_tpu.common.tracing import get_tracer, wall_time_us

# Rolling window (seconds) the live MFU / FLOP-rate gauges average over.
DEFAULT_WINDOW_S = 60.0

# Per-dispatch wall-clock: 100us (a warm small-batch CPU matmul) up to
# ~26s (a cold remote-compile dispatch).
DISPATCH_SECONDS_BUCKETS = exponential_buckets(1e-4, 4.0, 10)

# Occupancy is a ratio in (0, 1]: linear buckets, 0.05 steps (rounded so
# the top bucket renders le="1", not a float-summation tail).
OCCUPANCY_BUCKETS = tuple(round(b, 2) for b in linear_buckets(0.05, 0.05, 20))

# Bytes moved per dispatch: 4 KiB .. 16 GiB.
BYTES_BUCKETS = exponential_buckets(4096.0, 4.0, 12)


class DispatchRecord:
    """One device dispatch's cost accounting."""

    __slots__ = (
        "kind", "t_start", "wall_s", "flops", "bytes_moved",
        "rows", "padded_rows", "valid_rows", "capacity_rows",
        "occupancy", "trace_id", "score_mode", "seq",
    )

    def __init__(
        self,
        kind: str,
        t_start: float,
        wall_s: float,
        flops: float,
        bytes_moved: float,
        rows: int,
        padded_rows: int,
        valid_rows: int,
        capacity_rows: int,
        trace_id: str | None,
        score_mode: str | None = None,
    ):
        self.kind = kind
        self.t_start = t_start
        self.wall_s = wall_s
        self.flops = flops
        self.bytes_moved = bytes_moved
        self.rows = rows
        self.padded_rows = padded_rows
        self.valid_rows = valid_rows
        self.capacity_rows = capacity_rows
        # the metric the smoke contract pins: real rows over the
        # capacity-padded shape actually scored — always in [0, 1], never
        # NaN: a zero-capacity or empty dispatch (drained shutdown batch,
        # a caller passing garbage rows) must not poison the histogram
        # with a >1.0 or non-finite sample
        if capacity_rows > 0 and valid_rows > 0:
            occ = valid_rows / capacity_rows
            self.occupancy = min(1.0, occ) if occ == occ else 0.0
        else:
            self.occupancy = 0.0
        self.trace_id = trace_id
        # serving score mode (exact | quantized | approx) when the
        # dispatcher labels it; None for unlabeled kinds (train)
        self.score_mode = score_mode
        self.seq = -1

    def chrome_event(self, pid: int) -> dict:
        """This record as a Chrome trace-event `X` slice (Perfetto)."""
        return {
            "name": f"device.dispatch.{self.kind}",
            "cat": "oryx-perf",
            "ph": "X",
            "ts": wall_time_us(self.t_start),
            "dur": max(0.0, self.wall_s) * 1e6,
            "pid": pid,
            "tid": 1 if self.kind == "serving" else 2,
            "args": {
                "flops": self.flops,
                "bytes_moved": self.bytes_moved,
                "rows": self.rows,
                "padded_rows": self.padded_rows,
                "valid_rows": self.valid_rows,
                "capacity_rows": self.capacity_rows,
                "occupancy": round(self.occupancy, 4),
                "trace_id": self.trace_id or "",
                "score_mode": self.score_mode or "",
            },
        }


class PerfStats:
    """Process-wide dispatch-cost accounting: bounded record ring, rolling
    MFU, fallback-window suppression, and profile-window capture.

    Writers claim ring slots through an ``itertools.count`` (atomic under
    the GIL) like the tracing ring — dispatchers and the train loop never
    block each other on the record path."""

    def __init__(self, capacity: int = 4096, window_s: float = DEFAULT_WINDOW_S):
        self._buf: list[DispatchRecord | None] = [None] * max(64, capacity)
        self._seq = itertools.count()
        self.window_s = float(window_s)
        # Exact windowed FLOP accounting, separate from the debug ring:
        # the ring is bounded by SLOTS and silently drops oldest records,
        # so a busy window (> capacity dispatches) would truncate an
        # MFU computed from it exactly when the system is busiest. The
        # per-kind deque + running sum is bounded by TIME instead —
        # pruned on every append/read — so the rolling numerator is exact
        # at any dispatch rate. The ring stays as the /debug/profile and
        # records_since substrate.
        self._win: dict[str, "deque[tuple[float, float]]"] = {}  # guarded-by: _win_lock
        self._win_sum: dict[str, float] = {}  # guarded-by: _win_lock
        self._win_lock = threading.Lock()
        # chip peak FLOP/s per kind; Ellipsis = not yet resolved. An
        # operator-configured assumed peak (oryx.monitoring.perf.
        # assumed-peak-flops) stands in where no honest chip peak exists.
        self._peak: dict[str, float | None | type(...)] = {}
        self.assumed_peak_flops: float | None = None
        # a device→host fallback zeroes the KIND's MFU gauge until this
        # stamp: host-scored throughput must not wear the device's MFU
        # figure (per kind — a serving failover must not also zero an
        # unaffected co-resident train loop's gauge)
        self._fallback_until: dict[str, float] = {}
        # /debug/profile knobs (oryx.monitoring.profile.*)
        self.profile_enabled = False
        self.profile_max_seconds = 30.0
        self.profile_dir: str | None = None
        self._capture_lock = threading.Lock()
        self._register_lock = threading.Lock()

    # -- configuration -----------------------------------------------------

    def configure(self, config) -> None:
        """Adopt the oryx.monitoring.perf / oryx.monitoring.profile keys
        (each layer runtime calls this at construction; last writer wins,
        the one-config-per-process convention)."""
        self.window_s = float(
            config.get_float("oryx.monitoring.perf.window-sec", DEFAULT_WINDOW_S)
        )
        assumed = config.get("oryx.monitoring.perf.assumed-peak-flops", None)
        self.assumed_peak_flops = float(assumed) if assumed is not None else None
        self.profile_enabled = config.get_bool(
            "oryx.monitoring.profile.enabled", False
        )
        self.profile_max_seconds = float(
            config.get_float("oryx.monitoring.profile.max-seconds", 30.0)
        )
        self.profile_dir = config.get_string("oryx.monitoring.profile.dir", None)

    def ensure_peak(self, kind: str, resolver) -> None:
        """Resolve the chip peak for ``kind`` exactly once (resolver may
        touch jax and must only be called from a context where the
        backend is already live — never a scrape path)."""
        if self._peak.get(kind, ...) is not ...:
            return
        try:
            self._peak[kind] = resolver()
        except Exception:
            self._peak[kind] = None

    def note_peak(self, kind: str, peak: float | None) -> None:
        """Adopt an already-resolved chip peak (the batcher resolves it
        from an on-device array at dispatch time)."""
        if self._peak.get(kind, ...) is ...:
            self._peak[kind] = peak

    def set_peak(self, kind: str, peak: float | None) -> None:
        """Overwrite the kind's peak unconditionally: the batcher resolves
        a PER-DTYPE peak per dispatch (ops/flops.py tables), so the MFU
        gauge's denominator follows the dtype actually dispatched — a
        quantized int8 window reads against the int8 peak, never
        flattering itself against bf16."""
        self._peak[kind] = peak

    def peak_for(self, kind: str) -> float | None:
        peak = self._peak.get(kind, ...)
        if peak is ... or peak is None:
            return self.assumed_peak_flops
        return peak

    # -- recording ---------------------------------------------------------

    def record_dispatch(
        self,
        kind: str,
        *,
        flops: float,
        bytes_moved: float,
        wall_s: float,
        rows: int,
        padded_rows: int,
        valid_rows: int,
        capacity_rows: int,
        trace_id: str | None = None,
        t_start: float | None = None,
        score_mode: str | None = None,
    ) -> DispatchRecord:
        rec = DispatchRecord(
            kind,
            t_start if t_start is not None else time.monotonic() - wall_s,
            wall_s, flops, bytes_moved, rows, padded_rows, valid_rows,
            capacity_rows, trace_id, score_mode,
        )
        rec.seq = next(self._seq)
        buf = self._buf
        buf[rec.seq % len(buf)] = rec
        with self._win_lock:
            self._prune_window(kind, rec.t_start + wall_s)
            self._win.setdefault(kind, deque()).append(
                (rec.t_start + wall_s, flops)
            )
            self._win_sum[kind] = self._win_sum.get(kind, 0.0) + flops
        self._h_dispatch.observe(wall_s, trace_id=trace_id, kind=kind)
        self._h_occupancy.observe(rec.occupancy, trace_id=trace_id, kind=kind)
        self._h_bytes.observe(bytes_moved, kind=kind)
        if score_mode:
            # per-mode dispatch accounting: dashboards separate exact /
            # quantized / approx traffic without new histogram families
            self._c_score_mode.inc(score_mode=score_mode)
        return rec

    def note_fallback(self, n: int = 1, kind: str = "serving") -> None:
        """n requests were scored on the host because the device path
        failed (dispatch/transfer error or wedge failover). Counted, and
        the KIND's MFU gauge is zeroed for one rolling window — host
        throughput during the outage must not read as device
        utilization (other kinds' gauges are unaffected)."""
        if n <= 0:
            return
        self._c_fallback.inc(n)
        self._fallback_until[kind] = time.monotonic() + self.window_s
        from oryx_tpu.common.flightrec import get_flightrec

        # episode-limited: a sustained outage records one event per 5s,
        # not one per degraded request
        get_flightrec().record(
            kind="fallback", episode_s=5.0, n=n, dispatch_kind=kind,
        )

    def _prune_window(self, kind: str, now: float) -> None:  # oryxlint: holds=_win_lock
        """Drop window entries older than window_s (caller holds
        _win_lock)."""
        dq = self._win.get(kind)
        if not dq:
            return
        cutoff = now - self.window_s
        total = self._win_sum.get(kind, 0.0)
        while dq and dq[0][0] < cutoff:
            total -= dq.popleft()[1]
        self._win_sum[kind] = total if dq else 0.0

    # -- reading -----------------------------------------------------------

    def records_since(self, t: float) -> list[DispatchRecord]:
        """Records whose dispatch started at/after monotonic time t,
        oldest first."""
        recs = [
            r for r in list(self._buf)
            if r is not None and r.t_start >= t
        ]
        recs.sort(key=lambda r: r.seq)
        return recs

    def achieved_flops_per_sec(self, kind: str) -> float:
        """FLOP/s over the rolling window (0.0 when idle). Exact at any
        dispatch rate — the windowed accumulator is time-bounded, unlike
        the slot-bounded debug ring."""
        with self._win_lock:
            self._prune_window(kind, time.monotonic())
            total = self._win_sum.get(kind, 0.0)
        return total / self.window_s if total else 0.0

    def window_occupancy(self, kind: str) -> tuple[float | None, int]:
        """(mean dispatch batch occupancy over the rolling window, number
        of dispatches it averages) — (None, 0) when the window is idle.
        The fleet autoscaler's scale-DOWN signal: sustained low occupancy
        means the padding headroom is mostly waste and the fleet has more
        replicas than the offered load fills."""
        recs = [
            r for r in self.records_since(time.monotonic() - self.window_s)
            if r.kind == kind
        ]
        if not recs:
            return None, 0
        return sum(r.occupancy for r in recs) / len(recs), len(recs)

    def mfu(self, kind: str) -> float:
        """Rolling-window MFU in [0,1]; 0.0 during the kind's fallback
        window; NaN when no peak (chip or assumed) is known."""
        if time.monotonic() < self._fallback_until.get(kind, 0.0):
            return 0.0
        peak = self.peak_for(kind)
        if not peak or peak <= 0:
            return float("nan")
        return self.achieved_flops_per_sec(kind) / peak

    # -- profile windows ---------------------------------------------------

    def capture_profile(self, seconds: float) -> dict:
        """Block for ``seconds`` capturing every dispatch record in the
        window (plus, when tracing is enabled, the finished spans), and —
        when ``oryx.monitoring.profile.dir`` is set — a jax.profiler
        device trace written under that directory. Returns a
        Perfetto-loadable Chrome trace-event dict with an ``oryx`` meta
        block summarizing the window. Raises RuntimeError if a capture is
        already in flight (the jax profiler is process-global)."""
        import os

        if not self._capture_lock.acquire(blocking=False):
            raise RuntimeError("a profile capture is already running")
        try:
            t0 = time.monotonic()
            jax_trace_path = None
            profiler_started = False
            if self.profile_dir:
                jax_trace_path = os.path.join(
                    self.profile_dir, f"ondemand-{int(time.time() * 1000)}"
                )
                try:
                    import jax

                    jax.profiler.start_trace(jax_trace_path)
                    profiler_started = True
                except Exception:
                    jax_trace_path = None
            try:
                time.sleep(max(0.0, seconds))
            finally:
                if profiler_started:
                    try:
                        import jax

                        jax.profiler.stop_trace()
                    except Exception:
                        pass
            recs = self.records_since(t0)
            pid = os.getpid()
            events = [r.chrome_event(pid) for r in recs]
            tr = get_tracer()
            spans = 0
            if tr.enabled:
                from oryx_tpu.common.tracing import chrome_trace

                window_spans = [
                    s for s in tr.snapshot() if s.start >= t0
                ]
                events.extend(chrome_trace(window_spans)["traceEvents"])
                spans = len(window_spans)
            per_kind: dict[str, dict] = {}
            for r in recs:
                agg = per_kind.setdefault(
                    r.kind,
                    {"dispatches": 0, "flops": 0.0, "bytes": 0.0,
                     "wall_s": 0.0, "occupancy_sum": 0.0},
                )
                agg["dispatches"] += 1
                agg["flops"] += r.flops
                agg["bytes"] += r.bytes_moved
                agg["wall_s"] += r.wall_s
                agg["occupancy_sum"] += r.occupancy
            window = max(1e-9, time.monotonic() - t0)
            summary = {}
            for kind, agg in per_kind.items():
                peak = self.peak_for(kind)
                summary[kind] = {
                    "dispatches": agg["dispatches"],
                    "flops": agg["flops"],
                    "bytes_moved": agg["bytes"],
                    "busy_fraction": round(agg["wall_s"] / window, 4),
                    "mean_occupancy": round(
                        agg["occupancy_sum"] / agg["dispatches"], 4
                    ),
                    "flops_per_sec": agg["flops"] / window,
                    # no fixed-decimal rounding: honest MFUs here run
                    # 1e-8..1e-2 and a 6-decimal round would zero them
                    "mfu": (
                        agg["flops"] / window / peak if peak else None
                    ),
                }
            return {
                "displayTimeUnit": "ms",
                "traceEvents": events,
                "oryx": {
                    "window_seconds": round(window, 3),
                    "dispatch_records": len(recs),
                    "trace_spans": spans,
                    "jax_trace_dir": jax_trace_path,
                    "by_kind": summary,
                },
            }
        finally:
            self._capture_lock.release()

    # -- metrics -----------------------------------------------------------

    def ensure_metrics(self) -> None:
        """Register the perf metric families on the global registry (safe
        to call repeatedly; serving/batch/speed runtimes all call it so
        dashboards get the zero baseline from process start)."""
        reg = get_registry()
        with self._register_lock:
            self._h_dispatch = reg.histogram(
                "oryx_device_dispatch_seconds",
                "Wall-clock per device dispatch (coalesced serving top-k "
                "group or train-scan chunk), by kind",
                buckets=DISPATCH_SECONDS_BUCKETS,
            )
            self._h_occupancy = reg.histogram(
                "oryx_dispatch_batch_occupancy",
                "Valid rows over the capacity-padded shape actually "
                "dispatched (1.0 = zero padding waste), by kind",
                buckets=OCCUPANCY_BUCKETS,
            )
            self._h_bytes = reg.histogram(
                "oryx_device_bytes_per_dispatch",
                "Approximate bytes moved per device dispatch (operand "
                "streams + host transfers), by kind",
                buckets=BYTES_BUCKETS,
            )
            self._c_fallback = reg.counter(
                "oryx_device_fallback_dispatches_total",
                "Host-fallback scoring dispatches after a device error or "
                "wedge failover; each also zeroes oryx_device_mfu for one "
                "rolling window",
            )
            self._c_score_mode = reg.counter(
                "oryx_score_mode_dispatches_total",
                "Device top-k dispatches by serving score mode "
                "(score_mode = exact | quantized | approx); every "
                "batcher perfstats record carries the label",
                labeled=True,
            )
            # re-binding the same closures over the singleton is harmless,
            # and keeps the series alive across registry.clear() in tests
            g_mfu = reg.gauge(
                "oryx_device_mfu",
                "Rolling-window achieved MFU (FLOP/s over chip dense peak, "
                "or oryx.monitoring.perf.assumed-peak-flops); 0 during a "
                "host-fallback window, NaN when no peak is known",
                labeled=True,
            )
            g_rate = reg.gauge(
                "oryx_device_flops_per_sec",
                "Rolling-window achieved analytic FLOP/s of device "
                "dispatches, by kind",
                labeled=True,
            )
            for kind in ("serving", "train"):
                g_mfu.set_function(
                    (lambda k: lambda: self.mfu(k))(kind), kind=kind
                )
                g_rate.set_function(
                    (lambda k: lambda: self.achieved_flops_per_sec(k))(kind),
                    kind=kind,
                )


_default = PerfStats()
_default.ensure_metrics()


def get_perfstats() -> PerfStats:
    return _default


def configure_perfstats(config) -> PerfStats:
    _default.configure(config)
    _default.ensure_metrics()
    return _default
