"""Config-value dataflow checker (rule ``param-dropped``).

A config key read through a Config accessor into a variable represents
an operator's intent; a path that silently drops the value is the PR 11
``shard_mesh``-on-resume bug class — ``train_als_checkpointed`` accepted
``shard_mesh`` and forwarded it on the fresh path but not through its
resume chunks, so exactly the restarted long trains lost their sharding.

The rule: every ``x = config.get_*("oryx....")`` read must reach a sink
(call argument, attribute store, returned value, or control-flow use)
on **every** path of its function — and when it is handed to a project
function as a direct argument, the dataflow engine recurses into that
parameter with the same every-path requirement, so a wrapper in the
middle of the chain cannot absorb the value. ``# oryxlint: sink`` on a
use line declares an intentional terminal read.

Scope: modules under ``oryx_tpu/`` (bench/tools read config through ad
hoc plumbing that is not long-lived wiring).
"""

from __future__ import annotations

import ast

from tools.oryxlint.callgraph import shared_index
from tools.oryxlint.core import Checker, Finding, Project
from tools.oryxlint.dataflow import Dataflow

ACCESSOR_NAMES = frozenset({
    "get", "get_string", "get_int", "get_float", "get_bool", "get_list",
    "get_config", "has",
})


def _accessor_key(node: ast.AST) -> str | None:
    """The literal oryx.* key of a Config accessor call, if this node is
    one."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
        return None
    if node.func.attr not in ACCESSOR_NAMES or not node.args:
        return None
    k = node.args[0]
    if isinstance(k, ast.Constant) and isinstance(k.value, str) and (
        k.value.startswith("oryx.")
    ):
        return k.value
    return None


def _own_nodes(fn):
    """Nodes at this function's own level — nested defs are their own
    FunctionInfo and report their own reads."""
    stack = list(fn.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class ParamFlowChecker(Checker):
    name = "paramflow"
    rules = {
        "param-dropped": (
            "a config value read into a variable fails to reach a sink "
            "(call arg, attribute store, return) on every path of its "
            "function or of a callee it is handed to"
        ),
    }
    severities = {"param-dropped": "error"}
    fix_hints = {
        "param-dropped": (
            "thread the value through the dropping path (or annotate an "
            "intentional terminal read with `# oryxlint: sink`)"
        ),
    }

    def check(self, project: Project) -> list[Finding]:
        idx = shared_index(project)
        flow = Dataflow(idx)
        findings: list[Finding] = []
        seen: set[tuple[str, int, str]] = set()
        for fi in idx.functions:
            if not fi.module.relpath.startswith("oryx_tpu"):
                continue
            for stmt in _own_nodes(fi.node):
                if not (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                ):
                    continue
                key = None
                for sub in ast.walk(stmt.value):
                    key = _accessor_key(sub)
                    if key is not None:
                        break
                if key is None:
                    continue
                if stmt.lineno in fi.module.sink_lines:
                    continue  # annotated intentional terminal read
                name = stmt.targets[0].id
                for drop in flow.drops(fi, name, stmt.lineno):
                    site = (fi.module.relpath, drop.line, drop.reason)
                    if site in seen:
                        continue
                    seen.add(site)
                    findings.append(Finding(
                        fi.module.relpath, drop.line, "param-dropped",
                        f"config value of {key!r} (read at "
                        f"{fi.module.relpath}:{stmt.lineno} in "
                        f"{fi.qualname}): {drop.reason}",
                    ))
        return findings
