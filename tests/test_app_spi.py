"""SPI-conformance suite: every registered packaged app walks the SAME
contract checks (docs/apps.md), so a new app cannot silently skip a
hook or drift from the wiring the framework layers expect. Apps enter
via the registry (oryx_tpu/apps/spi.py) — adding a fifth app means
adding an AppSpec, and this suite picks it up automatically.
"""

from __future__ import annotations

import importlib

import pytest

from oryx_tpu.api import (
    BatchLayerUpdate,
    ServingModelManager,
    SpeedModelManager,
)
from oryx_tpu.apps.spi import all_apps, app_overlay, get_app
from oryx_tpu.bus.api import KeyMessage
from oryx_tpu.common.classutil import load_instance_of
from oryx_tpu.common.config import load_config

APPS = sorted(all_apps())


def _cfg(spec):
    return load_config(overlay={**app_overlay(spec.name), **spec.example_overlay})


def test_registry_names_and_lookup():
    assert {"als", "kmeans", "rdf", "example", "seq"} <= set(APPS)
    with pytest.raises(ValueError):
        get_app("nosuchapp")
    for name in APPS:
        assert get_app(name).name == name


@pytest.mark.parametrize("name", APPS)
def test_overlay_wires_the_framework_keys(name):
    overlay = app_overlay(name)
    assert set(overlay) == {
        "oryx.batch.update-class",
        "oryx.speed.model-manager-class",
        "oryx.serving.model-manager-class",
        "oryx.serving.application-resources",
    }
    resources = overlay["oryx.serving.application-resources"]
    # every app serves the shared resource module plus at least its own
    assert "oryx_tpu.serving.resources.common" in resources


@pytest.mark.parametrize("name", APPS)
def test_classes_resolve_and_subclass_the_spi(name):
    spec = get_app(name)
    cfg = _cfg(spec)
    batch = load_instance_of(spec.batch_update, BatchLayerUpdate, cfg)
    speed = load_instance_of(spec.speed_manager, SpeedModelManager, cfg)
    serving = load_instance_of(spec.serving_manager, ServingModelManager, cfg)
    assert isinstance(batch, BatchLayerUpdate)
    assert isinstance(speed, SpeedModelManager)
    assert isinstance(serving, ServingModelManager)


@pytest.mark.parametrize("name", APPS)
def test_resource_modules_register(name):
    for mod_name in get_app(name).serving_resources:
        mod = importlib.import_module(mod_name)
        assert callable(getattr(mod, "register", None)), (
            f"{mod_name} lacks the register(app) entry point"
        )


@pytest.mark.parametrize("name", APPS)
def test_validate_records_contract(name):
    """validate_records must return one verdict per record, agree with
    validate_record element-wise, and accept everything when the app
    does not override the hooks (the layers skip the sweep then)."""
    spec = get_app(name)
    cfg = _cfg(spec)
    records = [
        KeyMessage(None, "u1,s1,i1,1000"),
        KeyMessage(None, "definitely,not,every,apps,format"),
        KeyMessage(None, ""),
    ]
    for cls_name, base in (
        (spec.batch_update, BatchLayerUpdate),
        (spec.speed_manager, SpeedModelManager),
    ):
        inst = load_instance_of(cls_name, base, cfg)
        verdicts = list(inst.validate_records(records))
        assert len(verdicts) == len(records)
        assert verdicts == [inst.validate_record(km) for km in records]


@pytest.mark.parametrize("name", APPS)
def test_speed_manager_contract(name):
    """build_updates on an empty micro-batch is a cheap no-op (the speed
    layer polls empty constantly), and close() is callable."""
    spec = get_app(name)
    inst = load_instance_of(spec.speed_manager, SpeedModelManager, _cfg(spec))
    assert list(inst.build_updates([])) == []
    inst.close()


@pytest.mark.parametrize("name", APPS)
def test_serving_manager_contract(name):
    """get_model() answers (None before any update is fine), and the
    read-only flag follows config."""
    spec = get_app(name)
    cfg = _cfg(spec).overlay({"oryx.serving.api.read-only": True})
    inst = load_instance_of(spec.serving_manager, ServingModelManager, cfg)
    model = inst.get_model()
    assert model is None or callable(model.fraction_loaded)
    assert inst.is_read_only() is True
    inst.close()


@pytest.mark.parametrize("name", APPS)
def test_finalize_generation_is_safe_without_a_build(name):
    """The batch layer calls finalize_generation after EVERY window
    persist, including generations whose build failed or built nothing —
    the hook must tolerate that (PR 4 staging contract)."""
    spec = get_app(name)
    inst = load_instance_of(spec.batch_update, BatchLayerUpdate, _cfg(spec))
    inst.finalize_generation(123456789)
