"""Async HTTP frontend: parity with the threaded frontend plus protocol
edge cases (keep-alive, bad requests, concurrent clients, auth 401s).

The reference runs one serving stack under Tomcat NIO; here the same
ServingApp runs under either frontend, so the same requests must behave
identically under both (oryx.serving.api.server = async | threaded).
"""

from __future__ import annotations

import gzip
import http.client
import json
import socket
import threading

import pytest

from oryx_tpu.bus.broker import get_broker
from oryx_tpu.common.config import load_config
from oryx_tpu.serving.server import ServingLayer

FRONTENDS = ("async", "threaded")


def _config(bus: str, frontend: str, **extra):
    overlay = {
        "oryx.input-topic.broker": bus,
        "oryx.update-topic.broker": bus,
        "oryx.serving.api.port": 0,
        "oryx.serving.api.server": frontend,
        "oryx.serving.model-manager-class": "oryx_tpu.apps.example.serving.ExampleServingModelManager",
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.example",
        ],
    }
    overlay.update(extra)
    return load_config(overlay=overlay)


def _setup_bus(bus: str):
    broker = get_broker(bus)
    for t in ("OryxInput", "OryxUpdate"):
        if not broker.topic_exists(t):
            broker.create_topic(t, 1)
    broker.send("OryxUpdate", "MODEL", json.dumps({"big": 1, "word": 2}))
    return broker


def _wait_ready(port: int) -> None:
    for _ in range(100):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            conn.request("GET", "/ready")
            if conn.getresponse().status == 200:
                conn.close()
                return
        except Exception:
            pass
        import time

        time.sleep(0.1)
    raise TimeoutError("serving layer never became ready")


@pytest.mark.parametrize("frontend", FRONTENDS)
def test_frontend_parity(frontend):
    """GET/POST/HEAD/404/keep-alive behave identically on both frontends."""
    bus = f"mem://aserver-{frontend}"
    _setup_bus(bus)
    with ServingLayer(_config(bus, frontend)) as sl:
        _wait_ready(sl.port)
        conn = http.client.HTTPConnection("127.0.0.1", sl.port, timeout=5)

        # several requests on ONE keep-alive connection
        for _ in range(3):
            conn.request("GET", "/distinct")
            r = conn.getresponse()
            assert r.status == 200
            assert json.loads(r.read())["word"] == 2

        # HEAD: headers only
        conn.request("HEAD", "/ready")
        r = conn.getresponse()
        assert r.status == 200
        assert r.read() == b""

        # 404 with JSON error body
        conn.request("GET", "/no-such-endpoint")
        r = conn.getresponse()
        assert r.status == 404
        assert json.loads(r.read())["status"] == 404

        # POST /add ingests a line
        conn.request("POST", "/add", body=b"hello world")
        r = conn.getresponse()
        assert r.status in (200, 204)
        r.read()

        # gzipped request body is transparently decompressed
        conn.request(
            "POST",
            "/add",
            body=gzip.compress(b"more words"),
            headers={"Content-Encoding": "gzip"},
        )
        r = conn.getresponse()
        assert r.status in (200, 204)
        r.read()
        conn.close()


@pytest.mark.parametrize("frontend", FRONTENDS)
def test_frontend_auth_challenge(frontend):
    """Unauthenticated requests get a 401 digest challenge; authenticated
    clients succeed (urllib's digest handler drives the RFC exchange)."""
    import urllib.request

    bus = f"mem://aserver-auth-{frontend}"
    _setup_bus(bus)
    cfg = _config(
        bus,
        frontend,
        **{
            "oryx.serving.api.user-name": "oryx",
            "oryx.serving.api.password": "secret",
        },
    )
    with ServingLayer(cfg) as sl:
        url = f"http://127.0.0.1:{sl.port}/ready"
        conn = http.client.HTTPConnection("127.0.0.1", sl.port, timeout=5)
        conn.request("GET", "/ready")
        r = conn.getresponse()
        assert r.status == 401
        challenge = r.getheader("WWW-Authenticate")
        assert challenge and challenge.startswith("Digest")
        r.read()
        conn.close()

        mgr = urllib.request.HTTPPasswordMgrWithDefaultRealm()
        mgr.add_password(None, url, "oryx", "secret")
        opener = urllib.request.build_opener(
            urllib.request.HTTPDigestAuthHandler(mgr)
        )
        with opener.open(url, timeout=5) as resp:
            assert resp.status == 200


def test_async_concurrent_clients():
    """32 threads hammer one async server; every response is correct."""
    bus = "mem://aserver-conc"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)
        errs: list[str] = []

        def worker():
            try:
                conn = http.client.HTTPConnection("127.0.0.1", sl.port, timeout=10)
                for _ in range(20):
                    conn.request("GET", "/distinct/word")
                    r = conn.getresponse()
                    body = r.read()
                    if r.status != 200 or json.loads(body) != 2:
                        errs.append(f"bad response {r.status} {body[:80]!r}")
                conn.close()
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=worker) for _ in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs[:5]


def test_async_malformed_requests():
    """Garbage on the socket gets a 400 (or a clean close), never a hang."""
    bus = "mem://aserver-bad"
    _setup_bus(bus)
    with ServingLayer(_config(bus, "async")) as sl:
        _wait_ready(sl.port)

        # bad request line
        s = socket.create_connection(("127.0.0.1", sl.port), timeout=5)
        s.sendall(b"NONSENSE\r\n\r\n")
        data = s.recv(4096)
        assert data == b"" or b"400" in data.split(b"\r\n")[0]
        s.close()

        # huge declared content-length is rejected, not buffered
        s = socket.create_connection(("127.0.0.1", sl.port), timeout=5)
        s.sendall(
            b"POST /add HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 99999999999\r\n\r\n"
        )
        data = s.recv(4096)
        assert b"400" in data.split(b"\r\n")[0]
        s.close()

        # server is still healthy afterwards
        conn = http.client.HTTPConnection("127.0.0.1", sl.port, timeout=5)
        conn.request("GET", "/ready")
        assert conn.getresponse().status == 200
        conn.close()


def test_route_precedence():
    """Literal first segments beat parameter-first patterns regardless of
    registration order; within a group, registration order wins."""
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import Request, ServingApp

    class _Mgr:
        def get_model(self):
            return None

    app = ServingApp(load_config(overlay={}), _Mgr())

    @app.route("GET", "/{anything}")
    def wildcard(a, req):
        return "wildcard"

    @app.route("GET", "/specific")
    def specific(a, req):
        return "literal"

    @app.route("GET", "/specific")
    def shadowed(a, req):  # same pattern, registered later: must lose
        return "shadowed"

    def get(path):
        req = Request("GET", path, {}, {}, b"", {"accept": "application/json"})
        return json.loads(app.dispatch(req)[1])

    assert get("/specific") == "literal"
    assert get("/other") == "wildcard"


def test_threaded_frontend_reuse_port():
    """With processes > 1 configured, the threaded frontend also binds with
    SO_REUSEPORT — two servers share one port inside one process."""
    import socket as _socket

    if not hasattr(_socket, "SO_REUSEPORT"):
        pytest.skip("no SO_REUSEPORT")
    from oryx_tpu.common.ioutil import choose_free_port

    bus = "mem://aserver-rp"
    _setup_bus(bus)
    port = choose_free_port()
    cfg = _config(
        bus, "threaded",
        **{"oryx.serving.api.port": port, "oryx.serving.api.processes": 2},
    )
    with ServingLayer(cfg), ServingLayer(cfg):
        # /ready only proves ONE of the two kernel-balanced servers has
        # loaded its model; poll fresh connections until several in a row
        # succeed so both sockets are warm before asserting
        import time as _time

        deadline = _time.time() + 30
        streak = 0
        while _time.time() < deadline and streak < 6:
            try:
                c = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
                c.request("GET", "/distinct/word")
                r = c.getresponse()
                body = r.read()
                c.close()
                if r.status == 200 and json.loads(body) == 2:
                    streak += 1
                    continue
            except Exception:
                pass
            streak = 0
            _time.sleep(0.1)
        assert streak >= 6, "both reuse-port servers never became ready"


def test_recommend_dispatch_is_deferred():
    """The recommend-family endpoints must not park the dispatch thread:
    dispatch_nowait returns a Deferred whose future completes with the
    rendered response (the async frontend awaits it with no worker held)."""
    import numpy as np

    from oryx_tpu.apps.als.serving import ALSServingModel, ALSServingModelManager
    from oryx_tpu.apps.als.state import ALSState
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import Deferred, Request, ServingApp

    rng = np.random.default_rng(0)
    state = ALSState(4, implicit=True)
    state.y.bulk_set(["i0", "i1", "i2"], rng.standard_normal((3, 4), dtype=np.float32))
    state.x.bulk_set(["u0"], rng.standard_normal((1, 4), dtype=np.float32))
    state.set_expected(["u0"], ["i0", "i1", "i2"])
    cfg = load_config(
        overlay={
            "oryx.serving.application-resources": [
                "oryx_tpu.serving.resources.common",
                "oryx_tpu.serving.resources.als",
            ]
        }
    )
    mgr = ALSServingModelManager(cfg)
    mgr.model = ALSServingModel(state, sample_rate=1.0)
    app = ServingApp(cfg, mgr)

    resp = app.dispatch_nowait(
        Request("GET", "/recommend/u0", {}, {"howMany": ["2"]}, b"",
                {"accept": "application/json"})
    )
    assert isinstance(resp, Deferred)
    status, body, ctype = resp.future.result(timeout=30)
    assert status == 200
    import json

    assert len(json.loads(body)) == 2
    # blocking dispatch() keeps its synchronous contract on the same route
    status2, body2, _ = app.dispatch(
        Request("GET", "/recommend/u0", {}, {"howMany": ["2"]}, b"",
                {"accept": "application/json"})
    )
    assert status2 == 200 and json.loads(body2) == json.loads(body)


def test_wedge_failover_under_concurrent_http_load(monkeypatch):
    """32 concurrent /recommend requests parked on a wedged device must ALL
    be drained to host scoring by the watchdog (concurrent drain path) and
    the server must keep serving degraded — through the real async
    frontend, not the batcher API."""
    import http.client
    import threading as _threading
    import time as _time

    import numpy as np

    import oryx_tpu.ops.als as als_mod
    from oryx_tpu.apps.als.serving import ALSServingModel, ALSServingModelManager
    from oryx_tpu.apps.als.state import ALSState
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.batcher import TopKBatcher
    from oryx_tpu.serving.server import ServingLayer

    rng = np.random.default_rng(0)
    state = ALSState(8, implicit=True)
    state.y.bulk_set(
        [f"i{j}" for j in range(300)], rng.standard_normal((300, 8), dtype=np.float32)
    )
    state.x.bulk_set(
        [f"u{j}" for j in range(40)], rng.standard_normal((40, 8), dtype=np.float32)
    )
    state.set_expected(state.x.ids(), state.y.ids())
    cfg = load_config(overlay={
        "oryx.id": "chaos",
        "oryx.input-topic.broker": "mem://chaos",
        "oryx.update-topic.broker": "mem://chaos",
        "oryx.serving.api.port": 0,
        "oryx.serving.api.read-only": True,
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.als",
        ],
    })
    from oryx_tpu.bus.broker import topics

    topics.maybe_create("mem://chaos", "OryxUpdate", partitions=1)
    mgr = ALSServingModelManager(cfg)
    mgr.model = ALSServingModel(state, sample_rate=1.0)
    serving = ServingLayer(cfg, model_manager=mgr)
    serving.start()
    from e2e_common import WedgeHook

    b = TopKBatcher.shared()
    hook = None
    try:
        # no recovery mid-test; zero compile grace so the simulated wedge
        # (not a cold compile) trips the watchdog at device_timeout
        b.device_timeout, b.probe_interval = 1.0, 600.0
        b.compile_timeout = 0.0
        b._compiling.clear()  # clear grace left by earlier dispatches
        hook = WedgeHook(als_mod.topk_dot_batch, block_first_only=False, timeout=60)
        monkeypatch.setattr(als_mod, "topk_dot_batch", hook)

        results = [None] * 32

        def client(i):
            conn = http.client.HTTPConnection("127.0.0.1", serving.port, timeout=60)
            conn.request("GET", f"/recommend/u{i}?howMany=5")
            r = conn.getresponse()
            body = r.read()
            results[i] = (r.status, body)
            conn.close()

        threads = [_threading.Thread(target=client, args=(i,)) for i in range(32)]
        t0 = _time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        dt = _time.time() - t0
        assert all(r is not None and r[0] == 200 for r in results), [
            r[0] if r else None for r in results
        ]
        assert dt < 25, f"drain took {dt:.1f}s"
        assert b.device_failovers >= 1
        assert b.host_fallbacks >= 1
        # degraded path still serves new traffic
        conn = http.client.HTTPConnection("127.0.0.1", serving.port, timeout=30)
        conn.request("GET", "/recommend/u0?howMany=3")
        r = conn.getresponse()
        assert r.status == 200 and r.read()
        conn.close()
    finally:
        # ALWAYS unblock the wedged dispatcher and shut the batcher down —
        # an assertion failure above must not leak a spinning watchdog or
        # a thread parked in the hook for the rest of the session; each
        # teardown step runs even if an earlier one raises
        if hook is not None:
            hook.release.set()
        try:
            serving.close()
        finally:
            try:
                b.close()
            finally:
                TopKBatcher._shared = None


def test_multi_loop_frontend_serves_on_every_loop():
    """loops=4: four SO_REUSEPORT event loops share one port and ONE app;
    under many short-lived connections the kernel spreads traffic so every
    loop serves requests, responses stay correct, and the per-loop
    counters surface in /metrics."""
    import re

    bus = "mem://aserver-loops"
    _setup_bus(bus)
    cfg = _config(bus, "async", **{"oryx.serving.api.loops": 4})
    with ServingLayer(cfg) as sl:
        _wait_ready(sl.port)
        states = sl._aio_server._loopstates
        assert len(states) == 4
        errs: list[str] = []

        def worker():
            try:
                # fresh connection per request: each new 4-tuple re-rolls
                # the kernel's reuseport balancing, spreading load across
                # loops (128 connections over 4 loops never miss one)
                for _ in range(8):
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", sl.port, timeout=10
                    )
                    conn.request("GET", "/distinct/word")
                    r = conn.getresponse()
                    body = r.read()
                    conn.close()
                    if r.status != 200 or json.loads(body) != 2:
                        errs.append(f"bad response {r.status} {body[:80]!r}")
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=worker) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs[:5]
        assert all(ls.requests > 0 for ls in states), [
            ls.requests for ls in states
        ]
        # the same counters are scrapeable: oryx_http_loop_requests{loop=i}
        conn = http.client.HTTPConnection("127.0.0.1", sl.port, timeout=5)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        series = dict(
            re.findall(r'oryx_http_loop_requests\{loop="(\d)"\} (\d+)', text)
        )
        for i in range(4):
            assert int(series[str(i)]) > 0, series


def test_multi_loop_cross_loop_coalescing(monkeypatch):
    """Requests arriving on DIFFERENT event loops must coalesce into
    shared device dispatches: under concurrent /recommend load the
    process-wide batcher's dispatch count stays below its coalesced
    request count (mean batch > 1), and more than one loop carried
    traffic — coalescing across loops, not just port sharding."""
    import time as _time

    import numpy as np

    import oryx_tpu.ops.als as als_mod
    from oryx_tpu.apps.als.serving import ALSServingModel, ALSServingModelManager
    from oryx_tpu.apps.als.state import ALSState
    from oryx_tpu.bus.broker import topics
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.batcher import TopKBatcher

    rng = np.random.default_rng(0)
    state = ALSState(8, implicit=True)
    state.y.bulk_set(
        [f"i{j}" for j in range(500)],
        rng.standard_normal((500, 8), dtype=np.float32),
    )
    state.x.bulk_set(
        [f"u{j}" for j in range(32)],
        rng.standard_normal((32, 8), dtype=np.float32),
    )
    state.set_expected(state.x.ids(), state.y.ids())
    cfg = load_config(overlay={
        "oryx.id": "xloop",
        "oryx.input-topic.broker": "mem://xloop",
        "oryx.update-topic.broker": "mem://xloop",
        "oryx.serving.api.port": 0,
        "oryx.serving.api.read-only": True,
        "oryx.serving.api.loops": 4,
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.als",
        ],
    })
    topics.maybe_create("mem://xloop", "OryxUpdate", partitions=1)
    mgr = ALSServingModelManager(cfg)
    mgr.model = ALSServingModel(state, sample_rate=1.0)

    # hold each device dispatch briefly so concurrent arrivals pile into
    # the NEXT batch deterministically (the batcher's natural backpressure,
    # made test-stable on a fast CPU where dispatches are sub-ms)
    real = als_mod.topk_dot_batch

    def slowed(*a, **k):
        _time.sleep(0.01)
        return real(*a, **k)

    monkeypatch.setattr(als_mod, "topk_dot_batch", slowed)
    b = TopKBatcher.shared()
    d0, c0 = b.dispatches, b.coalesced

    with ServingLayer(cfg, model_manager=mgr) as sl:
        results: list = [None] * 32
        # each client keeps ONE keep-alive connection, so the 32
        # connections land on distinct loops via the kernel's balancing
        def client(i):
            conn = http.client.HTTPConnection("127.0.0.1", sl.port, timeout=60)
            ok = True
            for _ in range(4):
                conn.request("GET", f"/recommend/u{i}?howMany=5")
                r = conn.getresponse()
                body = r.read()
                ok = ok and r.status == 200 and len(json.loads(body)) == 5
            conn.close()
            results[i] = ok

        threads = [threading.Thread(target=client, args=(i,)) for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert all(results), results
        served = [ls.requests for ls in sl._aio_server._loopstates]

    coalesced = b.coalesced - c0
    dispatches = b.dispatches - d0
    assert coalesced == 128
    assert dispatches < coalesced, (dispatches, coalesced)
    assert sum(1 for n in served if n > 0) >= 2, served


def test_multi_loop_close_drains_every_loop():
    """close() on a multi-loop server must drain EVERY loop's parked
    keep-alive connections and stop every loop thread — not just loop 0's."""
    import time as _time

    from oryx_tpu.api import ServingModelManager
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import ServingApp
    from oryx_tpu.serving.aserver import AsyncHTTPServer

    class Manager(ServingModelManager):
        def __init__(self, config):
            self.config = config

        def consume(self, it):
            pass

        def get_model(self):
            return None

    cfg = load_config(overlay={
        "oryx.serving.application-resources": ["oryx_tpu.serving.resources.common"],
    })
    srv = AsyncHTTPServer(ServingApp(cfg, Manager(cfg)), None, 0, loops=3)
    srv.start()
    assert len(srv._loopstates) == 3
    conns = []
    try:
        for _ in range(12):
            c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
            c.request("GET", "/metrics")
            c.getresponse().read()  # keep-alive: stays parked on its loop
            conns.append(c)
        deadline = _time.time() + 5
        while len(srv._conns) < 12 and _time.time() < deadline:
            _time.sleep(0.02)
        assert len(srv._conns) == 12, "connection tasks never registered"
        t0 = _time.time()
    finally:
        srv.close()
    assert _time.time() - t0 < 4, "close() hung on parked connections"
    for ls in srv._loopstates:
        assert not ls.conns, f"loop {ls.index} leaked connection tasks"
        assert not ls.thread.is_alive(), f"loop {ls.index} thread survived close()"
    # close() must unbind its per-loop /metrics series immediately (not
    # wait for GC): stale series from a closed server would mislabel
    # loop counts on every later scrape — even while `srv` stays alive
    from oryx_tpu.common.metrics import get_registry

    text = get_registry().render_prometheus()
    assert 'oryx_http_loop_requests{loop=' not in text, text[:500]
    for c in conns:
        c.close()


def test_context_path_mounts_the_app():
    """oryx.serving.api.context-path prefixes every route (the reference's
    Tomcat context path); requests outside the prefix 404."""
    from oryx_tpu.api import ServingModelManager
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import Request, ServingApp

    class Manager(ServingModelManager):
        def __init__(self, config):
            self.config = config

        def consume(self, it):
            pass

        def get_model(self):
            return None

    cfg = load_config(overlay={
        "oryx.serving.api.context-path": "/oryx",
        "oryx.serving.application-resources": ["oryx_tpu.serving.resources.common"],
    })
    app = ServingApp(cfg, Manager(cfg))

    def get(path):
        return app.dispatch(
            Request("GET", path, {}, {}, b"", {"accept": "application/json"})
        )

    status, _, _ = get("/oryx/ready")
    assert status == 503  # routed (no model yet) — the prefix worked
    status, _, _ = get("/ready")
    assert status == 404  # outside the mount
    status, body, _ = get("/oryx/metrics")
    assert status == 200 and b"oryx_serving" in body


def test_close_cancels_parked_keepalive_connections():
    """close() must cancel connections parked in readuntil() — abandoned
    tasks die noisily with the loop ('Task was destroyed but it is
    pending') and can linger past shutdown."""
    import http.client
    import time as _time

    from oryx_tpu.api import ServingModelManager
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import ServingApp
    from oryx_tpu.serving.aserver import AsyncHTTPServer

    class Manager(ServingModelManager):
        def __init__(self, config):
            self.config = config

        def consume(self, it):
            pass

        def get_model(self):
            return None

    cfg = load_config(overlay={
        "oryx.serving.application-resources": ["oryx_tpu.serving.resources.common"],
    })
    srv = AsyncHTTPServer(ServingApp(cfg, Manager(cfg)), None, 0)
    srv.start()
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        conn.request("GET", "/metrics")
        conn.getresponse().read()  # keep-alive: connection stays parked
        deadline = _time.time() + 5
        while not srv._conns and _time.time() < deadline:
            _time.sleep(0.02)
        assert srv._conns, "connection task never registered"
        t0 = _time.time()
    finally:
        srv.close()
    assert _time.time() - t0 < 4, "close() hung on a parked connection"
    assert not srv._conns, "connection tasks leaked past close()"
    conn.close()
