"""Metrics registry, Prometheus rendering, and layer/serving integration.

The reference has no metrics subsystem (SURVEY.md §5); these cover the new
native one: counter/gauge/histogram semantics, exposition format, and the
serving layer's /metrics endpoint + request instrumentation.
"""

from __future__ import annotations

import threading

from oryx_tpu.common.metrics import (
    Counter,
    Gauge,
    GaugeSeriesGone,
    Histogram,
    MetricsRegistry,
    get_registry,
    maybe_profile,
)


def test_series_gone_eviction_spares_newer_binding():
    """A dead reader raising GaugeSeriesGone mid-scrape must evict only
    ITS binding: a new owner re-binding the same labels between the
    render snapshot and the raise keeps its fresh series."""
    g = Gauge("gone_rebind", "x")

    def new_reader():
        return 42.0

    def dead_reader():
        g.set_function(new_reader, loop="0")  # new owner rebinds mid-scrape
        raise GaugeSeriesGone("old owner gone")

    g.set_function(dead_reader, loop="0")
    g.render()  # dead reader raises; must NOT clobber the new binding
    assert g.value(loop="0") == 42.0
    assert 'gone_rebind{loop="0"} 42' in "\n".join(g.render())


def test_counter_inc_and_labels():
    c = Counter("reqs", "requests")
    c.inc()
    c.inc(2.0)
    c.inc(method="GET")
    assert c.value() == 3.0
    assert c.value(method="GET") == 1.0
    text = "\n".join(c.render())
    assert "# TYPE reqs counter" in text
    assert 'reqs{method="GET"} 1' in text
    assert "reqs 3" in text


def test_gauge_set_inc_dec_and_function():
    g = Gauge("frac", "fraction")
    g.set(0.5)
    g.inc(0.25)
    g.dec(0.5)
    assert abs(g.value() - 0.25) < 1e-9
    g.set_function(lambda: 0.9, kind="fn")
    assert g.value(kind="fn") == 0.9
    text = "\n".join(g.render())
    assert 'frac{kind="fn"} 0.9' in text


def test_histogram_buckets_cumulative():
    h = Histogram("lat", "latency", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count() == 4
    assert abs(h.sum() - 5.555) < 1e-9
    text = "\n".join(h.render())
    # cumulative: <=0.01 ->1, <=0.1 ->2, <=1 ->3, +Inf ->4
    assert 'lat_bucket{le="0.01"} 1' in text
    assert 'lat_bucket{le="0.1"} 2' in text
    assert 'lat_bucket{le="1"} 3' in text
    assert 'lat_bucket{le="+Inf"} 4' in text
    assert "lat_count 4" in text


def test_histogram_time_context():
    h = Histogram("t", "timing", buckets=(10.0,))
    with h.time(op="x"):
        pass
    assert h.count(op="x") == 1


def test_registry_same_name_returns_same_metric_and_kind_conflict():
    reg = MetricsRegistry()
    c1 = reg.counter("a", "first")
    c2 = reg.counter("a")
    assert c1 is c2
    try:
        reg.gauge("a")
        raise AssertionError("expected kind conflict")
    except ValueError:
        pass
    out = reg.render_prometheus()
    assert out.endswith("\n")


def test_registry_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("n", "")

    def work():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 8000


def test_help_text_escaped_in_exposition():
    """Newlines/backslashes in help must not corrupt the # HELP line (a
    raw newline would split the exposition mid-comment) — and quotes must
    NOT be escaped there (HELP allows only \\\\ and \\n escapes; \\" is
    itself invalid and would corrupt the scrape)."""
    c = Counter("esc", 'multi\nline help with \\backslash')
    text = "\n".join(c.render())
    assert '# HELP esc multi\\nline help with \\\\backslash' in text
    assert "\nline help" not in text  # no raw newline leaked
    q = Gauge("escq", 'the "auto" mode')
    assert '# HELP escq the "auto" mode' in "\n".join(q.render())


def test_labeled_only_metric_emits_no_zero_sample():
    """A labeled-only metric with zero series renders HELP/TYPE but NO
    bogus unlabeled `name 0` sample; an unlabeled counter keeps its 0."""
    c = Counter("labeled_reqs", "by loop", labeled=True)
    text = "\n".join(c.render())
    assert "# TYPE labeled_reqs counter" in text
    assert "labeled_reqs 0" not in text
    g = Gauge("labeled_g", "by shard", labeled=True)
    assert "labeled_g 0" not in "\n".join(g.render())
    # unlabeled metrics keep the explicit zero sample
    assert "plain 0" in "\n".join(Counter("plain", "x").render())
    c.inc(loop="0")
    assert 'labeled_reqs{loop="0"} 1' in "\n".join(c.render())


def test_read_paths_snapshot_under_lock():
    """value()/count()/sum() take the lock like render(): hammer reads
    against concurrent first-inserts (dict resizes) and verify totals."""
    c = Counter("rc", "")
    h = Histogram("rh", "", buckets=(1.0,))
    stop = []

    def write():
        for i in range(2000):
            c.inc(series=str(i))
            h.observe(0.5, series=str(i))
        stop.append(True)

    def read():
        while not stop:
            c.value(series="1")
            h.count(series="1")
            h.sum(series="1")

    w = threading.Thread(target=write)
    readers = [threading.Thread(target=read) for _ in range(4)]
    w.start()
    for r in readers:
        r.start()
    w.join()
    for r in readers:
        r.join()
    assert c.value(series="7") == 1.0
    assert h.count(series="7") == 1 and h.sum(series="7") == 0.5


def test_maybe_profile_noop_without_dir():
    with maybe_profile(None, "gen"):
        x = 1
    assert x == 1


def test_global_registry_is_singleton():
    assert get_registry() is get_registry()


def test_serving_metrics_endpoint(tmp_path):
    from oryx_tpu.api import ServingModelManager
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import Request, ServingApp

    class Manager(ServingModelManager):
        def __init__(self, config):
            self.config = config

        def consume(self, it):
            pass

        def get_model(self):
            return None

    cfg = load_config(
        overlay={"oryx.serving.application-resources": ["oryx_tpu.serving.resources.common"]}
    )
    app = ServingApp(cfg, Manager(cfg))

    def get(path):
        return app.dispatch(
            Request("GET", path, {}, {}, b"", {"accept": "application/json"})
        )

    # a request that 503s (no model) still gets counted
    status, _, _ = get("/ready")
    assert status == 503
    status, body, ctype = get("/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    text = body.decode()
    assert "oryx_serving_requests_total" in text
    assert 'method="GET"' in text
    assert "oryx_serving_model_load_fraction" in text
    assert "oryx_serving_request_seconds_bucket" in text


def test_als_model_bytes_gauge():
    """The ALS memory gauge reports the host arena bytes once a model is
    loaded (the reference's heap-per-model-size table analogue)."""
    import numpy as np

    from oryx_tpu.apps.als.serving import ALSServingModel, ALSServingModelManager
    from oryx_tpu.apps.als.state import ALSState
    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.metrics import get_registry
    from oryx_tpu.serving.app import ServingApp

    cfg = load_config(overlay={
        "oryx.serving.application-resources": [
            "oryx_tpu.serving.resources.common",
            "oryx_tpu.serving.resources.als",
        ],
    })
    state = ALSState(8, implicit=True)
    state.x.bulk_set(["u1", "u2"], np.ones((2, 8), dtype=np.float32))
    state.y.bulk_set(["i1"], np.ones((1, 8), dtype=np.float32))
    mgr = ALSServingModelManager(cfg)
    mgr.model = ALSServingModel(state)
    app = ServingApp(cfg, mgr)
    text = get_registry().render_prometheus()
    line = [l for l in text.splitlines() if l.startswith("oryx_als_model_bytes{")]
    assert line, text[-500:]
    assert float(line[0].rsplit(" ", 1)[1]) >= 3 * 8 * 4  # >= occupied bytes
    del app


def test_metrics_exposes_batcher_failover_gauges(tmp_path):
    """/metrics reports the top-k batcher's dispatch and wedged-device
    failover counters when the shared batcher exists (ops sizes an outage
    from oryx_topk_device_down + oryx_topk_host_fallbacks)."""
    from oryx_tpu.api import ServingModelManager
    from oryx_tpu.common.config import load_config
    from oryx_tpu.serving.app import Request, ServingApp
    from oryx_tpu.serving.batcher import TopKBatcher

    TopKBatcher.shared()  # ensure the shared instance exists

    class Manager(ServingModelManager):
        def __init__(self, config):
            self.config = config

        def consume(self, it):
            pass

        def get_model(self):
            return None

    cfg = load_config(
        overlay={"oryx.serving.application-resources": ["oryx_tpu.serving.resources.common"]}
    )
    app = ServingApp(cfg, Manager(cfg))
    status, body, _ = app.dispatch(
        Request("GET", "/metrics", {}, {}, b"", {"accept": "text/plain"})
    )
    assert status == 200
    text = body.decode()
    assert "oryx_topk_dispatches" in text
    assert "oryx_topk_host_fallbacks" in text
    assert "oryx_topk_device_down" in text
