"""Asyncio HTTP frontend for the serving layer.

The reference serving layer runs a 400-thread Tomcat with HTTP/1.1-NIO2 +
HTTP/2 connectors (framework/oryx-lambda-serving .../ServingLayer.java:
58-339). A thread-per-connection stdlib server is the Python analogue of
old blocking Tomcat; this module is the NIO analogue: an event loop owns
its connections (accept/read/write never hold a thread each), and only
the blocking part of a request — ``ServingApp.dispatch``, which may park
on the device micro-batcher — occupies a worker-pool thread. Connection
count therefore scales independently of thread count, and the worker pool
bounds in-flight dispatches the way Tomcat's executor bounds request
threads.

Multi-loop fan-out (``oryx.serving.api.loops``): the frontend runs N
acceptor/event-loop threads, EACH with its own ``SO_REUSEPORT`` listener
socket on the same port — the kernel balances connections across them —
but all sharing ONE ServingApp, ONE model manager, ONE worker pool, and
the ONE process-wide TopKBatcher. Unlike the full-replica mode
(``oryx.serving.api.processes``), which forks whole processes and
duplicates the HBM-resident factor matrices per replica, concurrent
requests from every loop coalesce into the SAME device dispatches:
bigger batches, fewer compiles, one model copy. Each loop's state
(connection registry, request counter) is touched only by its own
thread, so the loops share nothing mutable but the app itself.

Selected by ``oryx.serving.api.server = "async"`` (the default;
``"threaded"`` keeps the stdlib ThreadingHTTPServer path). Both frontends
share auth, gzip, and dispatch semantics; tests run the same suite against
each.
"""

from __future__ import annotations

import asyncio
import gzip
import logging
import socket
import ssl
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from urllib.parse import parse_qs, urlsplit

from oryx_tpu.common.perfattr import PhaseLedger, get_perfattr
from oryx_tpu.common.tracing import (
    format_traceparent,
    get_tracer,
    parse_traceparent,
)
from oryx_tpu.serving.app import Deferred, Request, ServingApp
from oryx_tpu.serving.auth import Authenticator

log = logging.getLogger(__name__)

# the tracer is a process singleton mutated in place by configure_tracing;
# binding it once keeps the disabled-tracing cost to one attribute read
# per request instead of a function call per stage
_TRACER = get_tracer()

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 256 * 1024 * 1024
READ_TIMEOUT = 30.0

_COMMON_STATUS = {
    200: b"200 OK",
    204: b"204 No Content",
    400: b"400 Bad Request",
    401: b"401 Unauthorized",
    404: b"404 Not Found",
    405: b"405 Method Not Allowed",
    500: b"500 Internal Server Error",
    503: b"503 Service Unavailable",
}


def _split_target(target: str) -> tuple[str, dict[str, list[str]]]:
    """Request target -> (path, query dict), skipping urlsplit + parse_qs
    allocation on the hot path. The common serving shapes
    (``?howMany=10``, ``?offsetSince=...``) carry no percent-escapes, no
    '+', and no blank values, so a straight split is exact; anything
    escaped/odd falls back to the stdlib parsers, byte-for-byte."""
    if target.startswith("/") and "#" not in target:
        q = target.find("?")
        if q < 0:
            return target, {}
        path, qs = target[:q], target[q + 1 :]
        if not qs:
            return path, {}
        if "%" not in qs and "+" not in qs:
            out: dict[str, list[str]] = {}
            for part in qs.split("&"):
                k, sep, v = part.partition("=")
                # parse_qs drops blank values and bare keys by default
                if sep and v:
                    bucket = out.get(k)
                    if bucket is None:
                        out[k] = [v]
                    else:
                        bucket.append(v)
            return path, out
        return path, parse_qs(qs)
    split = urlsplit(target)
    return split.path, parse_qs(split.query)


class _LoopState:
    """One event loop's private world: its thread, its SO_REUSEPORT
    listener, its live-connection registry, and its request counter.
    Everything here is touched only by the owning loop's thread (the
    counter is read, never written, by /metrics scrapes), so none of it
    needs a lock."""

    def __init__(self, index: int):
        self.index = index
        self.thread: threading.Thread | None = None
        self.loop: asyncio.AbstractEventLoop | None = None
        self.server: asyncio.AbstractServer | None = None
        # live per-connection tasks -> parked-between-requests flag
        self.conns: dict = {}
        # h1 requests + h2 streams served by this loop
        self.requests = 0
        self.started = threading.Event()
        self.error: BaseException | None = None


def _loop_requests_reader(ref):
    from oryx_tpu.common.metrics import GaugeSeriesGone

    def read() -> float:
        ls = ref()
        if ls is None:
            raise GaugeSeriesGone("event loop gone")
        return float(ls.requests)

    return read


class AsyncHTTPServer:
    """Multi-event-loop HTTP/1.1(+h2) server wrapping a ServingApp.

    Runs each asyncio loop on a dedicated thread so it presents the same
    synchronous start()/close() surface as the threaded frontend.
    """

    def __init__(
        self,
        app: ServingApp,
        auth: Authenticator | None,
        port: int,
        ssl_context: ssl.SSLContext | None = None,
        workers: int = 128,
        reuse_port: bool = False,
        loops: int = 1,
    ):
        self.app = app
        self.auth = auth
        self.port = port
        self._ssl = ssl_context
        self._reuse_port = reuse_port
        self.loops = max(1, loops)
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="oryx-serving-worker"
        )
        self._loopstates: list[_LoopState] = []
        self._want_reuse = False
        # (reader fn, loop label) bindings registered on the global
        # metrics registry, so close() can drop exactly them
        self._metric_bindings: list[tuple[object, str]] = []

    # -- introspection (tests + threaded-era callers) ----------------------

    @property
    def _conns(self) -> dict:
        """Merged view of every loop's live-connection registry (read-only:
        each loop owns its own dict)."""
        merged: dict = {}
        for ls in self._loopstates:
            merged.update(ls.conns)
        return merged

    @property
    def _thread(self) -> threading.Thread | None:
        return self._loopstates[0].thread if self._loopstates else None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        n = self.loops
        if n > 1 and not hasattr(socket, "SO_REUSEPORT"):
            log.warning(
                "oryx.serving.api.loops=%d but this platform has no "
                "SO_REUSEPORT; running a single event loop", n,
            )
            n = 1
        self._want_reuse = self._reuse_port or n > 1

        # loop 0 binds first and resolves an ephemeral port; the remaining
        # loops then join that CONCRETE port with SO_REUSEPORT
        first = _LoopState(0)
        self._loopstates = [first]
        self._start_loop(first)
        first.started.wait(timeout=30)
        if first.error is not None:
            raise first.error
        if first.server is None:
            raise RuntimeError("async serving frontend failed to start")

        rest = [_LoopState(i) for i in range(1, n)]
        self._loopstates.extend(rest)
        for ls in rest:
            self._start_loop(ls)
        for ls in rest:
            ls.started.wait(timeout=30)
            if ls.error is not None or ls.server is None:
                err = ls.error or RuntimeError(
                    f"serving event loop {ls.index} failed to start"
                )
                self.close()  # don't leave the earlier loops listening
                raise err
        self.app.loop_count = len(self._loopstates)  # surfaced by /healthz
        self._register_metrics()

    def _start_loop(self, ls: _LoopState) -> None:
        ls.thread = threading.Thread(
            target=self._run_loop, args=(ls,),
            name=f"oryx-serving-aio-{ls.index}", daemon=True,
        )
        ls.thread.start()

    def _register_metrics(self) -> None:
        """Per-loop request counters on the process-global registry:
        `oryx_http_loop_requests{loop="i"}`. Callback-bound (the loop
        thread owns the int; scrapes read it live) and weakly referenced
        so a closed server's series disappear instead of pinning it."""
        from oryx_tpu.common.metrics import get_registry

        c = get_registry().counter(
            "oryx_http_loop_requests",
            "HTTP requests served, by frontend event loop",
            labeled=True,  # zero series after close() renders no bogus `name 0`
        )
        for ls in self._loopstates:
            reader = _loop_requests_reader(weakref.ref(ls))
            c.set_function(reader, loop=str(ls.index))
            self._metric_bindings.append((reader, str(ls.index)))

    def close(self) -> None:
        # drain all loops CONCURRENTLY: each close is bounded by its own
        # grace window, and serializing N of them would multiply shutdown
        # latency by the loop count
        pending = []
        for ls in self._loopstates:
            if ls.loop is not None and ls.loop.is_running():
                pending.append(
                    (ls, asyncio.run_coroutine_threadsafe(
                        self._shutdown(ls), ls.loop
                    ))
                )
        for ls, fut in pending:
            try:
                fut.result(timeout=10)
            except Exception:  # pragma: no cover - defensive
                pass
            ls.loop.call_soon_threadsafe(ls.loop.stop)
        for ls in self._loopstates:
            if ls.thread is not None:
                ls.thread.join(timeout=10)
        self._pool.shutdown(wait=False)
        if self._metric_bindings:
            # drop OUR per-loop series now rather than waiting for GC: a
            # closed server's stale series would mislabel loop counts (and
            # ghost counter resets) on every later /metrics scrape. The
            # exact-fn unbind leaves a newer server's same-label bindings
            # untouched.
            from oryx_tpu.common.metrics import get_registry

            c = get_registry().counter("oryx_http_loop_requests")
            for reader, label in self._metric_bindings:
                c.unbind_function(reader, loop=label)
            self._metric_bindings = []

    def join(self) -> None:
        """Block until every loop thread exits (serving-layer
        await_termination)."""
        for ls in self._loopstates:
            if ls.thread is not None:
                ls.thread.join()

    async def _shutdown(self, ls: _LoopState) -> None:
        if ls.server is not None:
            ls.server.close()
        # Drain BEFORE wait_closed(): python 3.12's Server.wait_closed
        # waits for all connection handlers, so waiting first silently
        # burned close()'s full timeout and abandoned tasks to die noisily
        # with the loop ("Task was destroyed but it is pending").
        # Idle keep-alive connections (parked in readuntil) cancel
        # immediately; BUSY requests get a short grace to finish writing
        # their response, then cancel too. The sweep loops because a
        # connection accepted just before close() registers only on its
        # task's first step.
        loop = asyncio.get_running_loop()
        grace_until = loop.time() + 5.0
        while True:
            # yield first: a handler task created for a just-accepted
            # connection registers only on its first step — checking
            # before yielding would miss it entirely
            await asyncio.sleep(0)
            if not ls.conns:
                break
            past_grace = loop.time() >= grace_until
            for task, idle in list(ls.conns.items()):
                if past_grace or idle:
                    task.cancel()
            await asyncio.wait(list(ls.conns), timeout=0.25)
        if ls.server is not None:
            await ls.server.wait_closed()

    def _run_loop(self, ls: _LoopState) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        ls.loop = loop
        try:
            ls.server = loop.run_until_complete(
                asyncio.start_server(
                    lambda r, w: self._handle_conn(ls, r, w),
                    "0.0.0.0",
                    self.port,
                    ssl=self._ssl,
                    backlog=1024,
                    # one listener per loop (and/or per replica process)
                    # on the same port; the kernel load-balances
                    # connections across them
                    reuse_port=self._want_reuse or None,
                )
            )
            if ls.index == 0:
                self.port = ls.server.sockets[0].getsockname()[1]
        except BaseException as e:  # surface bind errors to start()
            ls.error = e
            ls.started.set()
            loop.close()
            return
        ls.started.set()
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()

    # -- per-connection protocol ------------------------------------------

    async def _handle_conn(
        self,
        ls: _LoopState,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            ls.conns[task] = True  # idle until a request head arrives
            task.add_done_callback(lambda t: ls.conns.pop(t, None))
        try:
            while True:
                try:
                    head = await asyncio.wait_for(
                        reader.readuntil(b"\r\n\r\n"), timeout=READ_TIMEOUT
                    )
                except (
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError,
                    ConnectionError,
                ):
                    return
                except asyncio.LimitOverrunError:
                    await self._simple_response(writer, 400, b"headers too large")
                    return
                if len(head) > MAX_HEADER_BYTES:
                    await self._simple_response(writer, 400, b"headers too large")
                    return
                # head received: the parse stage (and, when tracing is on,
                # the request span) starts here — the phase ledger needs
                # the stamp regardless of tracing
                t_parse = time.monotonic()
                if task is not None:
                    ls.conns[task] = False  # request in flight

                if head == b"PRI * HTTP/2.0\r\n\r\n":
                    # HTTP/2 with prior knowledge (also the path ALPN-
                    # negotiated h2-over-TLS arrives on): consume the
                    # rest of the 24-byte preface and hand over; the h2
                    # connection stays bound to THIS loop's state
                    from oryx_tpu.serving.http2 import Http2Connection

                    rest = await asyncio.wait_for(
                        reader.readexactly(6), timeout=READ_TIMEOUT
                    )
                    if rest != b"SM\r\n\r\n":
                        return
                    await Http2Connection(self, reader, writer, owner=ls).run(
                        preface_read=True
                    )
                    return

                lines = head.split(b"\r\n")
                try:
                    method_b, target_b, version_b = lines[0].split(b" ", 2)
                    method = method_b.decode("ascii")
                    target = target_b.decode("ascii")
                except (ValueError, UnicodeDecodeError):
                    await self._simple_response(writer, 400, b"bad request line")
                    return
                headers: dict[str, str] = {}
                for ln in lines[1:]:
                    if not ln:
                        continue
                    i = ln.find(b":")
                    if i <= 0:
                        continue
                    headers[ln[:i].decode("latin-1").lower()] = (
                        ln[i + 1 :].strip().decode("latin-1")
                    )

                if "chunked" in headers.get("transfer-encoding", "").lower():
                    await self._simple_response(
                        writer, 400, b"chunked bodies not supported"
                    )
                    return
                try:
                    length = int(headers.get("content-length") or 0)
                except ValueError:
                    await self._simple_response(writer, 400, b"bad content-length")
                    return
                if length > MAX_BODY_BYTES:
                    await self._simple_response(writer, 400, b"body too large")
                    return
                body = b""
                if length:
                    try:
                        body = await asyncio.wait_for(
                            reader.readexactly(length), timeout=READ_TIMEOUT
                        )
                    except (
                        asyncio.IncompleteReadError,
                        asyncio.TimeoutError,
                        ConnectionError,
                    ):
                        return

                connection_opts = {
                    t.strip().lower()
                    for t in headers.get("connection", "").split(",")
                }
                if (
                    "upgrade" in connection_opts
                    and headers.get("upgrade", "").lower() == "h2c"
                    and "http2-settings" in headers
                ):
                    # h2c upgrade (RFC 7540 §3.2): validate the client's
                    # HTTP2-Settings BEFORE the 101 — a malformed payload
                    # is a malformed REQUEST (§3.2.1) and must get a 400
                    # over h1, not a protocol error after switching
                    from oryx_tpu.serving.http2 import (
                        Http2Connection,
                        decode_h2c_settings,
                    )

                    if decode_h2c_settings(headers["http2-settings"]) is None:
                        writer.write(
                            b"HTTP/1.1 400 Bad Request\r\n"
                            b"Content-Length: 0\r\nConnection: close\r\n\r\n"
                        )
                        await writer.drain()
                        return
                    writer.write(
                        b"HTTP/1.1 101 Switching Protocols\r\n"
                        b"Connection: Upgrade\r\nUpgrade: h2c\r\n\r\n"
                    )
                    await writer.drain()
                    await Http2Connection(
                        self, reader, writer,
                        upgraded_request=(method, target, headers, body),
                        owner=ls,
                    ).run(preface_read=False)
                    return

                keep_alive = (
                    headers.get("connection", "").lower() != "close"
                    and version_b != b"HTTP/1.0"
                )
                await self._handle_request(
                    writer, method, target, headers, body, parse_start=t_parse
                )
                ls.requests += 1
                if task is not None:
                    ls.conns[task] = True  # parked between requests
                if not keep_alive:
                    return
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _process(
        self,
        method: str,
        target: str,
        headers: dict[str, str],
        body: bytes,
        span=None,
        ledger=None,
    ) -> tuple[int, bytes, str, tuple[tuple[str, str], ...]]:
        """Auth + gzip-decode + route dispatch, shared by every loop's
        HTTP/1.1 handler and the HTTP/2 streams (serving/http2.py):
        returns (status, payload, content-type, extra response headers).

        ``span`` is the request span when the h1 path already opened one;
        h2 streams call with span=None and (when tracing is on) get a
        request span owned — opened AND finished — here. ``ledger``
        follows the same ownership rule: the h1 path passes the one it
        created at parse time; h2 streams get one created AND flushed
        here (their frame writes aren't observable per request)."""
        tr = _TRACER
        own_span = False
        if span is None and tr.enabled:
            span = tr.start(
                "http.request",
                parent=parse_traceparent(headers.get("traceparent")),
                method=method, target=target, proto="h2",
            )
            own_span = True
        own_ledger = ledger is None
        if ledger is None:
            ledger = PhaseLedger(trace=span)
        elif span is not None and ledger.trace is None:
            ledger.trace = span
            ledger.trace_id = span.trace_id
        try:
            if self.auth is not None:
                t_auth = time.monotonic()
                verdict = self.auth.check(method, target, headers.get("authorization"))
                ledger.add("auth", time.monotonic() - t_auth, start=t_auth)
                if span is not None:
                    tr.record_interval("http.auth", t_auth, parent=span)
                if verdict is not True:
                    if span is not None:
                        span.attrs["status"] = 401
                    return (
                        401,
                        b'{"status":401,"error":"unauthorized"}',
                        "application/json",
                        (("WWW-Authenticate", verdict),),
                    )

            path, query = _split_target(target)
            if headers.get("content-encoding", "").lower() == "gzip" and body:
                import zlib

                try:
                    body = gzip.decompress(body)
                except (OSError, EOFError, zlib.error):
                    # OSError: bad magic; EOFError: truncated stream;
                    # zlib.error: corrupt deflate — all must 400, not
                    # escape and silently drop the connection
                    if span is not None:
                        span.attrs["status"] = 400
                    return 400, b"bad gzip body", "text/plain", ()
            req = Request(
                method=method,
                path=path,
                params={},
                query=query,
                body=body,
                headers=headers,
                trace=span,
                ledger=ledger,
            )
            loop = asyncio.get_running_loop()
            dspan = (
                tr.start("http.dispatch", parent=span, path=path)
                if span is not None
                else None
            )
            try:
                if self.app.is_fast(path):
                    # every route under this segment is declared nonblocking
                    # (state lookups + submit_nowait only): dispatch inline on
                    # the event loop, skipping two thread hops per request
                    resp = self.app.dispatch_nowait(req)
                else:
                    resp = await loop.run_in_executor(
                        self._pool, self.app.dispatch_nowait, req
                    )
                if isinstance(resp, Deferred):
                    # deferred endpoints (device-batched top-k) complete on the
                    # event loop: the worker thread is already free, so in-flight
                    # requests are bounded by memory, not by pool size
                    resp = await asyncio.wrap_future(resp.future)
                status, payload, ctype = resp
            except Exception:  # pragma: no cover - dispatch renders its own 500s
                log.exception("dispatch failed")
                status, payload, ctype = 500, b"internal error", "text/plain"
            if dspan is not None:
                tr.finish(dspan, status=status)
                span.attrs["status"] = status
            # headers accumulated during dispatch (Retry-After on sheds,
            # Warning on stale-model responses) — read AFTER any Deferred
            # completed, so chained handlers' headers are included too
            hdrs = list(req.response_headers)
            if span is not None:
                # traced responses name their trace: the id to look up in
                # /debug/traces and to match against /metrics exemplars
                hdrs.append((
                    "traceparent",
                    format_traceparent(span.trace_id, span.span_id),
                ))
            return status, payload, ctype, tuple(hdrs)
        finally:
            if own_ledger:
                get_perfattr().observe_request(ledger)
            if own_span:
                tr.finish(span)
                tr.log_if_slow(span, log)

    async def _handle_request(
        self,
        writer: asyncio.StreamWriter,
        method: str,
        target: str,
        headers: dict[str, str],
        body: bytes,
        parse_start: float = 0.0,
    ) -> None:
        tr = _TRACER
        span = None
        if tr.enabled:
            # the request span opens at head-received time so header parse
            # + body read are inside it; "http.parse" covers that stage
            start = parse_start or None
            span = tr.start(
                "http.request",
                parent=parse_traceparent(headers.get("traceparent")),
                start=start, method=method, target=target,
            )
            if parse_start:
                tr.record_interval("http.parse", parse_start, parent=span)
        ledger = PhaseLedger(trace=span)
        if parse_start:
            # head received -> request line/headers/body fully parsed
            ledger.add(
                "parse", time.monotonic() - parse_start, start=parse_start
            )
        status, payload, ctype, extra = await self._process(
            method, target, headers, body, span=span, ledger=ledger
        )
        gzip_ok = "gzip" in headers.get("accept-encoding", "").lower()
        t_resp = time.monotonic()
        await self._write_response(
            writer, status, payload, ctype, method, gzip_ok=gzip_ok, extra=extra
        )
        ledger.add("write", time.monotonic() - t_resp, start=t_resp)
        get_perfattr().observe_request(ledger)
        if span is not None:
            tr.record_interval("http.respond", t_resp, parent=span)
            tr.finish(span, status=status)
            tr.log_if_slow(span, log)

    # (status, ctype) -> precomputed header prefix; statuses and content
    # types are a tiny closed set, so this never grows unbounded.
    # _clen_cache extends the same pattern to the length-dependent tail:
    # rendered JSON responses cluster on a few dozen byte lengths, so the
    # common response writes two cached byte strings and the payload.
    _prefix_cache: dict = {}
    _clen_cache: dict = {}

    async def _write_response(
        self,
        writer: asyncio.StreamWriter,
        status: int,
        payload: bytes,
        ctype: str,
        method: str,
        gzip_ok: bool = False,
        extra: tuple[tuple[str, str], ...] = (),
    ) -> None:
        prefix = self._prefix_cache.get((status, ctype))
        if prefix is None:
            status_line = _COMMON_STATUS.get(status) or f"{status} Status".encode()
            prefix = (
                b"HTTP/1.1 " + status_line + b"\r\nContent-Type: "
                + ctype.encode("latin-1") + b"\r\nVary: Accept-Encoding"
            )
            if len(self._prefix_cache) < 512:
                self._prefix_cache[(status, ctype)] = prefix
        parts = [prefix]
        if gzip_ok and len(payload) >= 1024:
            payload = gzip.compress(payload, compresslevel=5)
            parts.append(b"\r\nContent-Encoding: gzip")
        for k, v in extra:
            parts.append(f"\r\n{k}: {v}".encode("latin-1"))
        n = len(payload)
        tail = self._clen_cache.get(n)
        if tail is None:
            tail = f"\r\nContent-Length: {n}\r\n\r\n".encode("ascii")
            if n < 8192 and len(self._clen_cache) < 8192:
                self._clen_cache[n] = tail
        parts.append(tail)
        if method != "HEAD":
            parts.append(payload)
        writer.write(b"".join(parts))
        try:
            await writer.drain()
        except ConnectionError:
            pass

    async def _simple_response(
        self, writer: asyncio.StreamWriter, status: int, msg: bytes
    ) -> None:
        await self._write_response(writer, status, msg, "text/plain", "GET")
