"""ALS collaborative filtering — pjit-sharded trainer + incremental fold-in.

TPU-native re-design of the reference's ALS compute path:

- Batch training replaces org.apache.spark.mllib.recommendation.ALS (invoked
  at app/oryx-app-mllib .../als/ALSUpdate.java:140-151) with alternating
  normal-equation solves: interactions become *padded per-entity lists*
  (static shapes for XLA), each half-iteration is one big batched
  gather -> einsum -> Cholesky-solve on the MXU, with the user/item axes
  sharded over the mesh "data" axis. The Gram matrix Y^T.Y is a sharded
  einsum (XLA inserts the psum the reference hand-rolled as a partition
  sum). Implicit feedback follows Hu-Koren-Volinsky confidence weighting
  (c = 1 + alpha.r), explicit uses ALS-WR lambda.n_u regularization to
  match MLlib behavior.

- Input preprocessing mirrors ALSUpdate semantics (…/als/ALSUpdate.java:
  348-422): per-day exponential decay of old interactions, zero-threshold
  drop, NaN-as-delete aggregation for implicit (NaN-propagating sum),
  last-wins for explicit, optional log1p(r/epsilon) strength transform.

- The speed/serving incremental fold-in mirrors ALSUtils.computeTargetQui/
  computeUpdatedXu (app/oryx-app-common .../als/ALSUtils.java:37-106):
  interpolate the predicted strength toward 1/0 by the interaction
  strength, then solve (Y^T.Y) dXu = dQui.Yi against the cached Cholesky
  factor — here jitted and vmappable over a whole micro-batch.
"""

from __future__ import annotations

import logging
import math
import weakref
from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

from oryx_tpu.common.rng import RandomManager
from oryx_tpu.ops.vector import gram


# ---------------------------------------------------------------------------
# host-side input preparation
# ---------------------------------------------------------------------------

@dataclass
class InteractionData:
    """Aggregated COO interactions with contiguous int ids."""

    user_ids: list[str]
    item_ids: list[str]
    users: np.ndarray  # [nnz] int32 indices into user_ids
    items: np.ndarray  # [nnz] int32 indices into item_ids
    values: np.ndarray  # [nnz] float32

    @property
    def n_users(self) -> int:
        return len(self.user_ids)

    @property
    def n_items(self) -> int:
        return len(self.item_ids)


def aggregate_interactions(
    users: np.ndarray,
    items: np.ndarray,
    values: np.ndarray,
    timestamps: np.ndarray | None = None,
    *,
    implicit: bool = True,
    decay_factor: float = 1.0,
    zero_threshold: float = 0.0,
    now_ms: int | None = None,
    log_strength: bool = False,
    epsilon: float = 1.0,
) -> InteractionData:
    """String-keyed raw events -> deduplicated COO with contiguous ids.

    Semantics parity with ALSUpdate: decay by factor^(days old), implicit
    NaN-propagating sum (NaN value = delete the pair), explicit last-wins by
    timestamp, drop aggregates <= zero-threshold (implicit), log-strength
    transform after aggregation. ID maps are sorted for determinism, like
    the reference's sorted zipWithIndex maps (ALSUpdate.java:180-189).
    """
    users = np.asarray(users)
    items = np.asarray(items)
    values = np.asarray(values, dtype=np.float64)
    n = len(values)
    ts = (
        np.asarray(timestamps, dtype=np.int64)
        if timestamps is not None
        else np.zeros(n, dtype=np.int64)
    )

    if decay_factor < 1.0 and now_ms is not None:
        # calendar-day ages (now's day-of-epoch minus the event's), not a
        # rolling 24h difference: an event's decay bucket is then a pure
        # function of ITS timestamp, so the incremental AggregateState can
        # store raw per-day sums and apply decay at view time — at any
        # later generation — and still match this from-scratch path
        # exactly. (The reference decays by whole days too.)
        days_old = np.maximum(0, now_ms // _DAY_MS - ts // _DAY_MS)
        values = values * np.power(decay_factor, days_old)

    uid_sorted, ui = _factorize_string_ids(users)
    iid_sorted, ii = _factorize_string_ids(items)
    pair = ui * len(iid_sorted) + ii

    if implicit:
        # NaN-propagating sum per pair: any NaN (delete marker) kills the pair
        uniq, inv = np.unique(pair, return_inverse=True)
        sums = np.zeros(len(uniq))
        np.add.at(sums, inv, values)  # NaN propagates into the bucket sum
        keep = ~np.isnan(sums) & (np.abs(sums) > zero_threshold) & (sums > 0)
        agg_pair, agg_val = uniq[keep], sums[keep]
    else:
        # last (by timestamp) wins; NaN final value = delete
        order = np.lexsort((ts, pair))
        pair_s, val_s = pair[order], values[order]
        last = np.r_[pair_s[1:] != pair_s[:-1], True]
        agg_pair, agg_val = pair_s[last], val_s[last]
        keep = ~np.isnan(agg_val)
        agg_pair, agg_val = agg_pair[keep], agg_val[keep]

    if log_strength:
        agg_val = np.log1p(np.maximum(agg_val, 0.0) / epsilon)

    au = (agg_pair // len(iid_sorted)).astype(np.int32)
    ai = (agg_pair % len(iid_sorted)).astype(np.int32)
    return InteractionData(uid_sorted, iid_sorted, au, ai, agg_val.astype(np.float32))


_DAY_MS = 86_400_000

_POW10 = 10 ** np.arange(1, 19, dtype=np.int64)


def _factorize_string_ids(arr: np.ndarray) -> tuple[list[str], np.ndarray]:
    """(lexicographically sorted distinct ids, index-per-row) — the
    vectorized form of the reference's sorted-distinct ID maps
    (ALSUpdate.java:180-189). np.unique on tens of millions of strings is
    a minutes-scale host bottleneck, so ids that are canonical decimal
    integers (the common case: MovieLens et al.) take an O(n) bincount
    factorization instead; anything else falls back to np.unique."""
    arr = np.asarray(arr)
    if arr.size == 0:
        return [], np.zeros(0, dtype=np.int64)
    if arr.dtype.kind in "iu":
        # already integer ids (e.g. from the native data loader, which only
        # accepts canonical decimal tokens) — no string checks needed
        nums = arr.astype(np.int64)
        canonical = True
    else:
        if arr.dtype.kind != "U":
            arr = arr.astype(str)
        try:
            nums = arr.astype(np.int64)
        except (ValueError, OverflowError):
            nums = None
        canonical = False
        if nums is not None and np.abs(nums).max() < 10**17:
            # canonical form check by exact digit count: rejects "07", "+7",
            # " 7", "-0" — strings astype(int) accepts but str() won't emit
            a = np.abs(nums)
            canon_len = np.searchsorted(_POW10, a, side="right") + 1 + (nums < 0)
            canonical = bool((np.char.str_len(arr) == canon_len).all())
    if nums is not None and canonical:
        lo = int(nums.min())
        span = int(nums.max()) - lo + 1
        if span <= max(4 * len(nums), 1 << 28):
            present = np.zeros(span, dtype=bool)
            present[nums - lo] = True
            uniq = np.nonzero(present)[0] + lo
            rank = np.cumsum(present) - 1
            inv = rank[nums - lo]
        else:
            uniq, inv = np.unique(nums, return_inverse=True)
        # remap numeric order -> lexicographic, for parity with the
        # reference's sorted string ids (only the small unique array
        # pays the string sort)
        uniq_strs = uniq.astype(str)
        lex = np.argsort(uniq_strs)
        perm = np.empty_like(lex)
        perm[lex] = np.arange(len(lex))
        return uniq_strs[lex].tolist(), perm[inv.astype(np.int64)]
    ids, inv = np.unique(arr, return_inverse=True)
    return ids.tolist(), inv.astype(np.int64)


# ---------------------------------------------------------------------------
# incremental aggregate state: aggregate_interactions, made mergeable
# ---------------------------------------------------------------------------

AGG_STATE_SCHEMA = 1


def _group_sum(u, i, d, v, presorted: bool = False):
    """Group (user, item[, day]) keys and NaN-propagating-sum their
    values: the ONE grouping kernel behind AggregateState's from_window,
    merge, and materialize paths — the stable lexsort keeps earlier
    entries (history order) first within a group, so partial sums add in
    the order the equivalence property test pins. d=None groups by
    (user, item) only. Returns (u_sorted, i_sorted, d_sorted, first_idx,
    sums) with one sums entry per group, first_idx naming each group's
    first sorted row."""
    if d is None:
        d = np.zeros(len(u), dtype=np.int64)
    if not presorted:
        order = np.lexsort((d, i, u))
        u, i, d, v = u[order], i[order], d[order], v[order]
    new = np.r_[
        True, (u[1:] != u[:-1]) | (i[1:] != i[:-1]) | (d[1:] != d[:-1])
    ]
    grp = np.cumsum(new) - 1
    sums = np.zeros(int(grp[-1]) + 1)
    np.add.at(sums, grp, v)  # NaN (delete marker) propagates into its group
    return u, i, d, np.nonzero(new)[0], sums


def agg_state_fingerprint(*, implicit: bool, with_days: bool) -> str:
    """Schema fingerprint a persisted snapshot must match to be loadable.
    zero-threshold / log-strength / the decay FACTOR are view-time
    parameters (materialize()) and deliberately absent: changing them must
    not force a full history re-read. Turning decay on/off changes the
    stored granularity (day buckets) and does."""
    return f"agg-v{AGG_STATE_SCHEMA}:implicit={implicit}:days={with_days}"


@dataclass
class AggregateState:
    """Persistent, mergeable form of ``aggregate_interactions``.

    Invariant: ``merge`` over any windowing of a history, then
    ``materialize``, equals ``aggregate_interactions`` over the
    concatenated history (bit-identical under exact float arithmetic;
    within rounding otherwise — the merge reorders sums only).

    - implicit: one entry per (user, item, day bucket) holding the raw
      NaN-propagating strength sum of that bucket. NaN (the delete
      marker) is KEPT in the state: any later strength added to a dead
      pair stays NaN, exactly like the full-history NaN-propagating sum.
      Decay is day-of-epoch (see aggregate_interactions), so a bucket's
      weight at any generation is ``sum * decay^(now_day - day)`` — decay
      never re-ages the stored sums. With decay off the day axis
      collapses to one bucket.
    - explicit: one entry per (user, item) holding (last_ts, raw last
      value); merges keep the newer timestamp, ties going to the newer
      window — the same winner the from-scratch stable lexsort picks.
      NaN value = delete, kept for the same resurrection-proofing.

    zero-threshold / positivity / log-strength are applied by
    ``materialize`` only: a pair below threshold this generation can come
    back above it later, exactly as a from-scratch re-aggregation would
    see it. Entries stay sorted by (user, item, day).
    """

    implicit: bool
    with_days: bool
    user_ids: np.ndarray  # [U] unicode, lexicographically sorted
    item_ids: np.ndarray  # [I] unicode, lexicographically sorted
    users: np.ndarray     # [M] int64 index into user_ids
    items: np.ndarray     # [M] int64 index into item_ids
    days: np.ndarray      # [M] int64 day-of-epoch bucket (0 when unused)
    vals: np.ndarray      # [M] float64 sums (implicit) / last value (explicit)
    last_ts: np.ndarray   # [M] int64 (explicit last-wins key; 0 when implicit)

    @property
    def entries(self) -> int:
        return len(self.vals)

    @property
    def fingerprint(self) -> str:
        return agg_state_fingerprint(
            implicit=self.implicit, with_days=self.with_days
        )

    @staticmethod
    def empty(*, implicit: bool, with_days: bool) -> "AggregateState":
        z = np.zeros(0, dtype=np.int64)
        return AggregateState(
            implicit, with_days,
            np.zeros(0, dtype="<U1"), np.zeros(0, dtype="<U1"),
            z.copy(), z.copy(), z.copy(), np.zeros(0, dtype=np.float64),
            z.copy(),
        )

    # -- construction --------------------------------------------------

    @staticmethod
    def from_window(
        users: np.ndarray,
        items: np.ndarray,
        values: np.ndarray,
        timestamps: np.ndarray | None = None,
        *,
        implicit: bool = True,
        with_days: bool = False,
    ) -> "AggregateState":
        """Aggregate ONE window of raw events into state form (the same
        id factorization and within-window combine rules as
        aggregate_interactions, minus the view-time transforms)."""
        users = np.asarray(users)
        items = np.asarray(items)
        values = np.asarray(values, dtype=np.float64)
        n = len(values)
        ts = (
            np.asarray(timestamps, dtype=np.int64)
            if timestamps is not None
            else np.zeros(n, dtype=np.int64)
        )
        if n == 0:
            return AggregateState.empty(implicit=implicit, with_days=with_days)
        uid_sorted, ui = _factorize_string_ids(users)
        iid_sorted, ii = _factorize_string_ids(items)
        uid_arr = np.asarray(uid_sorted, dtype=str)
        iid_arr = np.asarray(iid_sorted, dtype=str)
        ui = ui.astype(np.int64)
        ii = ii.astype(np.int64)
        day = (ts // _DAY_MS) if (implicit and with_days) else np.zeros(n, np.int64)
        if implicit:
            u_s, i_s, d_s, first, sums = _group_sum(ui, ii, day, values)
            return AggregateState(
                implicit, with_days, uid_arr, iid_arr,
                u_s[first], i_s[first], d_s[first], sums,
                np.zeros(len(first), dtype=np.int64),
            )
        # explicit: last (by timestamp) wins; stable sort breaks ties by
        # position in the window, like the from-scratch lexsort
        order = np.lexsort((ts, ii, ui))
        u_s, i_s, t_s, v_s = ui[order], ii[order], ts[order], values[order]
        last = np.r_[(u_s[1:] != u_s[:-1]) | (i_s[1:] != i_s[:-1]), True]
        keep = np.nonzero(last)[0]
        return AggregateState(
            implicit, with_days, uid_arr, iid_arr,
            u_s[keep], i_s[keep], np.zeros(len(keep), dtype=np.int64),
            v_s[keep], t_s[keep],
        )

    # -- merge -----------------------------------------------------------

    def merge(self, window: "AggregateState") -> "AggregateState":
        """Fold a newer window's state into this one: O(state + window),
        never O(history). ``window`` must be the NEWER side (explicit
        timestamp ties resolve toward it)."""
        if (self.implicit, self.with_days) != (window.implicit, window.with_days):
            raise ValueError("aggregate state schema mismatch")
        if window.entries == 0 and len(window.user_ids) == 0:
            return self
        if self.entries == 0 and len(self.user_ids) == 0:
            return window
        uids = np.union1d(self.user_ids, window.user_ids)
        iids = np.union1d(self.item_ids, window.item_ids)
        su = np.searchsorted(uids, self.user_ids)[self.users]
        si = np.searchsorted(iids, self.item_ids)[self.items]
        wu = np.searchsorted(uids, window.user_ids)[window.users]
        wi = np.searchsorted(iids, window.item_ids)[window.items]
        u = np.concatenate([su, wu])
        i = np.concatenate([si, wi])
        d = np.concatenate([self.days, window.days])
        v = np.concatenate([self.vals, window.vals])
        t = np.concatenate([self.last_ts, window.last_ts])
        if self.implicit:
            u_s, i_s, d_s, first, sums = _group_sum(u, i, d, v)
            return AggregateState(
                self.implicit, self.with_days, uids, iids,
                u_s[first], i_s[first], d_s[first], sums,
                np.zeros(len(first), dtype=np.int64),
            )
        # explicit: newest timestamp per pair wins; stable sort puts the
        # window's entry after the state's on equal ts, so ties go to it
        order = np.lexsort((t, i, u))
        u, i, v, t = u[order], i[order], v[order], t[order]
        last = np.r_[(u[1:] != u[:-1]) | (i[1:] != i[:-1]), True]
        keep = np.nonzero(last)[0]
        return AggregateState(
            self.implicit, self.with_days, uids, iids,
            u[keep], i[keep], np.zeros(len(keep), dtype=np.int64),
            v[keep], t[keep],
        )

    # -- view ------------------------------------------------------------

    def materialize(
        self,
        *,
        decay_factor: float = 1.0,
        zero_threshold: float = 0.0,
        now_ms: int | None = None,
        log_strength: bool = False,
        epsilon: float = 1.0,
    ) -> InteractionData:
        """The view-time half of aggregate_interactions: decay, delete/
        threshold filters and the log transform, over the merged state."""
        uid_list = self.user_ids.tolist()
        iid_list = self.item_ids.tolist()
        if self.implicit:
            w = self.vals
            if self.with_days and decay_factor < 1.0 and now_ms is not None:
                ages = np.maximum(0, now_ms // _DAY_MS - self.days)
                w = w * np.power(decay_factor, ages)
            if self.entries:
                # entries are already (user, item, day)-sorted: collapsing
                # the day axis groups by (user, item) in place
                u_s, i_s, _, first, sums = _group_sum(
                    self.users, self.items, None, w, presorted=True
                )
                pu, pi = u_s[first], i_s[first]
            else:
                sums = np.zeros(0)
                pu = pi = np.zeros(0, dtype=np.int64)
            keep = ~np.isnan(sums) & (np.abs(sums) > zero_threshold) & (sums > 0)
            agg_val = sums[keep]
            pu, pi = pu[keep], pi[keep]
        else:
            vals = self.vals
            if decay_factor < 1.0 and now_ms is not None:
                ages = np.maximum(0, now_ms // _DAY_MS - self.last_ts // _DAY_MS)
                vals = vals * np.power(decay_factor, ages)
            keep = ~np.isnan(vals)
            agg_val = vals[keep]
            pu, pi = self.users[keep], self.items[keep]
        if log_strength:
            agg_val = np.log1p(np.maximum(agg_val, 0.0) / epsilon)
        return InteractionData(
            uid_list, iid_list,
            pu.astype(np.int32), pi.astype(np.int32),
            agg_val.astype(np.float32),
        )

    # -- (de)serialization -------------------------------------------------

    def to_arrays(self) -> dict[str, np.ndarray]:
        """Compact columnar form for npz persistence (datastore snapshot)."""
        return {
            "user_ids": self.user_ids if self.user_ids.size else np.zeros(0, "<U1"),
            "item_ids": self.item_ids if self.item_ids.size else np.zeros(0, "<U1"),
            "users": self.users.astype(np.int64),
            "items": self.items.astype(np.int64),
            "days": self.days.astype(np.int64),
            "vals": self.vals.astype(np.float64),
            "last_ts": self.last_ts.astype(np.int64),
            "flags": np.asarray([int(self.implicit), int(self.with_days)], np.int64),
        }

    @staticmethod
    def from_arrays(arrays) -> "AggregateState":
        flags = np.asarray(arrays["flags"]).astype(np.int64)
        return AggregateState(
            bool(flags[0]), bool(flags[1]),
            np.asarray(arrays["user_ids"], dtype=str),
            np.asarray(arrays["item_ids"], dtype=str),
            np.asarray(arrays["users"], dtype=np.int64),
            np.asarray(arrays["items"], dtype=np.int64),
            np.asarray(arrays["days"], dtype=np.int64),
            np.asarray(arrays["vals"], dtype=np.float64),
            np.asarray(arrays["last_ts"], dtype=np.int64),
        )


def align_factors(
    prev_ids, prev_mat: np.ndarray | None, new_ids, features: int,
    seed_key=None,
) -> np.ndarray | None:
    """Map a previous generation's factor rows onto a new id table: ids
    retained across generations keep their learned rows, new ids get the
    cold random init (same scale as the trainers'). Returns None when
    there is nothing usable to resume from (no previous factors, or the
    feature width changed — a hyperparameter move cold-starts)."""
    if prev_mat is None or len(np.shape(prev_mat)) != 2:
        return None
    prev_mat = np.asarray(prev_mat, dtype=np.float32)
    if prev_mat.shape[1] != features or prev_mat.shape[0] == 0:
        return None
    prev_ids = np.asarray(prev_ids, dtype=str)
    new_ids = np.asarray(new_ids, dtype=str)
    order = np.argsort(prev_ids, kind="stable")
    prev_sorted, prev_rows = prev_ids[order], prev_mat[order]
    key = seed_key if seed_key is not None else RandomManager.get_key()
    # np.array (not asarray): jax hands back a read-only host view
    out = np.array(
        jax.random.normal(key, (len(new_ids), features), dtype=jnp.float32)
        * 0.1
        + 1.0 / math.sqrt(features)
    )
    pos = np.searchsorted(prev_sorted, new_ids)
    pos_c = np.clip(pos, 0, len(prev_sorted) - 1)
    hit = prev_sorted[pos_c] == new_ids
    out[hit] = prev_rows[pos_c[hit]]
    return out


def build_padded_lists(
    entity: np.ndarray,
    other: np.ndarray,
    values: np.ndarray,
    n_entities: int,
    cap: int = 1024,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Group COO by `entity` into static-shape padded lists.

    Returns (idx [N,P] int32, val [N,P] f32, mask [N,P] f32) with
    P = min(max row length, cap), power-of-2-padded for stable XLA tiling.
    Rows longer than P keep their largest-|value| interactions (the most
    informative ones) — the static-shape answer to Spark's ragged rows.
    """
    order = np.lexsort((-np.abs(values), entity))
    e, o, v = entity[order], other[order], values[order]
    counts = np.bincount(e, minlength=n_entities)
    max_c = int(counts.max()) if counts.size else 1
    p = 1 << max(0, (min(max_c, cap) - 1)).bit_length()
    p = max(p, 1)
    rank = np.arange(len(e)) - np.repeat(
        np.concatenate([[0], np.cumsum(counts)[:-1]]), counts
    )
    keep = rank < p
    e, o, v, rank = e[keep], o[keep], v[keep], rank[keep]
    idx = np.zeros((n_entities, p), dtype=np.int32)
    val = np.zeros((n_entities, p), dtype=np.float32)
    mask = np.zeros((n_entities, p), dtype=np.float32)
    idx[e, rank] = o
    val[e, rank] = v
    mask[e, rank] = 1.0
    return idx, val, mask


# ---------------------------------------------------------------------------
# the jitted trainer
# ---------------------------------------------------------------------------

def _half_step(
    factors, gram_f, idx, val, mask, lam, alpha, implicit: bool, block: int,
    compute_dtype=jnp.float32,
):
    """One ALS half-iteration: solve every row's normal equations.

    factors: [M,K] fixed side; idx/val/mask: [N,P] padded lists over the
    solving side. Processes rows in `block`-sized chunks via lax.map so the
    [B,P,K] gather never materializes for the whole axis at once.

    compute_dtype=bfloat16 feeds the dominant einsum bf16 inputs with f32
    accumulation (MXU-native single pass instead of multi-pass f32); the
    [K,K] systems and the Cholesky solves stay f32 either way.
    """
    n, p = idx.shape
    k = factors.shape[1]
    eye = jnp.eye(k, dtype=jnp.float32)
    nb = n // block
    # bf16 inputs accumulate exactly in f32 on the MXU; f32 inputs keep
    # the multi-pass HIGHEST path (plain f32 einsum on TPU rounds inputs
    # to bf16 anyway, which would silently degrade the default)
    prec = (
        jax.lax.Precision.DEFAULT
        if compute_dtype == jnp.bfloat16
        else jax.lax.Precision.HIGHEST
    )

    def one_block(args):
        bidx, bval, bmask = args
        yu = factors[bidx].astype(compute_dtype)  # [B,P,K] gather
        if implicit:
            # Hu et al.: A = Y'Y + Yu' diag(alpha.r) Yu + lam.I
            #            b = Yu' ((1 + alpha.r) . p),  p = 1 for observed
            w = alpha * bval * bmask
            a = (
                gram_f[None]
                + jnp.einsum("bpk,bp,bpl->bkl", yu, w.astype(compute_dtype), yu,
                             precision=prec,
                             preferred_element_type=jnp.float32)
                + lam * eye[None]
            )
            pref = (bval > 0).astype(jnp.float32) * bmask
            b = jnp.einsum("bpk,bp->bk", yu,
                           ((1.0 + w) * pref).astype(compute_dtype),
                           precision=prec,
                           preferred_element_type=jnp.float32)
        else:
            # ALS-WR: A = Yu'Yu + lam.n_u.I ; b = Yu' r
            a = jnp.einsum("bpk,bp,bpl->bkl", yu, bmask.astype(compute_dtype), yu,
                           precision=prec,
                           preferred_element_type=jnp.float32)
            n_u = bmask.sum(axis=1)
            a = a + (lam * jnp.maximum(n_u, 1.0))[:, None, None] * eye[None]
            b = jnp.einsum("bpk,bp->bk", yu, (bval * bmask).astype(compute_dtype),
                           precision=prec,
                           preferred_element_type=jnp.float32)
        chol = jnp.linalg.cholesky(a)
        # bf16-assembled normal equations can round a marginal system
        # indefinite (the MXU rounds einsum INPUTS to bf16; observed at
        # ML-25M scale: one failed factorization NaN-poisons gram() and
        # with it the whole next half-sweep). Retry non-finite rows with
        # trace-scaled jitter — the ALS analogue of the reference solver's
        # singularity guard (ops/solver.py; Solver.java ill-conditioned
        # check) — and zero whatever still fails: a zero row re-enters the
        # next half-sweep cleanly and is re-solved from scratch.
        ok = jnp.isfinite(chol).all(axis=(-2, -1), keepdims=True)
        jitter = (
            0.02 * jnp.trace(a, axis1=-2, axis2=-1) / k + 1e-6
        )[:, None, None]
        chol = jnp.where(
            ok, chol, jnp.linalg.cholesky(a + jitter * eye[None])
        )
        y = jax.scipy.linalg.solve_triangular(chol, b[..., None], lower=True)
        x = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(chol, -1, -2), y, lower=False
        )[..., 0]
        x = jnp.where(jnp.isfinite(x).all(axis=-1, keepdims=True), x, 0.0)
        # rows with no interactions (all-pad) solve to ~0 already (b = 0)
        return x

    blocks = jax.lax.map(
        one_block,
        (
            idx.reshape(nb, block, p),
            val.reshape(nb, block, p),
            mask.reshape(nb, block, p),
        ),
    )
    return blocks.reshape(n, k)


@partial(
    jax.jit,
    static_argnames=("implicit", "iterations", "block", "compute_dtype"),
)
def als_train_jit(
    u_idx, u_val, u_mask, i_idx, i_val, i_mask, y0, lam, alpha,
    *, implicit: bool, iterations: int, block: int,
    compute_dtype: str = "float32",
):
    """Full ALS training loop as one compiled program (lax.scan over
    iterations). All shapes static; shard u_* over users and i_* over items
    on the mesh "data" axis and XLA threads the collectives through."""
    cdt = jnp.dtype(compute_dtype)

    def body(carry, _):
        _, y = carry
        x = _half_step(
            y, gram(y), u_idx, u_val, u_mask, lam, alpha, implicit, block,
            compute_dtype=cdt,
        )
        y_new = _half_step(
            x, gram(x), i_idx, i_val, i_mask, lam, alpha, implicit, block,
            compute_dtype=cdt,
        )
        # x rides in the carry, NOT a per-step scan output: stacking it
        # would multiply peak factor memory by the iteration count
        return (x, y_new), None

    x0 = jnp.zeros((u_idx.shape[0], y0.shape[1]), dtype=jnp.float32)
    (x_fin, y_fin), _ = jax.lax.scan(body, (x0, y0), None, length=iterations)
    return x_fin, y_fin


@dataclass
class ALSModelArrays:
    x: np.ndarray  # [n_users, K]
    y: np.ndarray  # [n_items, K]
    user_ids: list[str]
    item_ids: list[str]


def _finish_model(x, y, n_u: int, n_i: int, data) -> ALSModelArrays:
    """Trim padding and surface solver-guard diagnostics. An all-zero
    factor row is almost always the _half_step singularity guard zeroing an
    unsolvable system in the final sweep (explicit rows whose aggregated
    ratings are all exactly zero also land here) — worth a warning, never
    worth a NaN."""
    x = np.asarray(x)[:n_u]
    y = np.asarray(y)[:n_i]
    zeroed = int((~x.any(axis=1)).sum() + (~y.any(axis=1)).sum())
    if zeroed:
        log.warning(
            "ALS: %d all-zero factor rows (singularity guard, or all-zero "
            "explicit ratings) of %d users + %d items", zeroed, n_u, n_i,
        )
    return ALSModelArrays(x, y, data.user_ids, data.item_ids)


def _record_train_dispatch(
    args, train_flops, train_s, n_u, n_i, n_u_pad, n_i_pad, features,
    compute_dtype,
) -> None:
    """Report one train-scan execution's cost (FLOPs, approximate bytes
    uploaded + factor tables back, wall-clock, row-padding occupancy) to
    the runtime perf accounting — the train-side twin of the serving
    batcher's per-dispatch records. Never lets accounting break training."""
    try:
        from oryx_tpu.common.perfstats import get_perfstats
        from oryx_tpu.ops.flops import device_peak_flops

        dtype = (
            "bfloat16" if str(compute_dtype).startswith("bf") else "float32"
        )
        ps = get_perfstats()
        # the backend is live here (the scan just ran), so resolving the
        # chip peak is safe — ensure_peak caches the one resolution
        ps.ensure_peak("train", lambda: device_peak_flops(dtype))
        bytes_moved = float(
            sum(
                getattr(a, "nbytes", 0)
                for bucket in args[0] + args[1]
                for a in bucket
            )
            + getattr(args[2], "nbytes", 0)
            + (n_u_pad + n_i_pad) * features * 4
        )
        ps.record_dispatch(
            "train",
            flops=train_flops, bytes_moved=bytes_moved, wall_s=train_s,
            rows=n_u + n_i, padded_rows=n_u_pad + n_i_pad,
            valid_rows=n_u + n_i, capacity_rows=n_u_pad + n_i_pad,
        )
    except Exception:  # pragma: no cover - accounting must not break builds
        pass


def train_als(
    data: InteractionData,
    features: int = 10,
    lam: float = 0.001,
    alpha: float = 1.0,
    iterations: int = 10,
    implicit: bool = True,
    mesh=None,
    cap: int = 1024,
    block: int = 1024,
    seed_key=None,
    compute_dtype: str = "float32",
    resume_y: np.ndarray | None = None,
    timings: dict | None = None,
    donate_y0: bool = False,
    shard_mesh=None,
) -> ALSModelArrays:
    """Train ALS factor matrices. If a mesh is given, the padded lists and
    factor tables are sharded over its "data" axis and the whole scan runs
    SPMD; a mesh with a non-trivial "model" axis dispatches to the
    tensor-parallel trainer (X sharded by user, Y by item — see
    train_als_tp); single-device otherwise. compute_dtype="bfloat16" feeds
    the normal-equation einsums bf16 inputs with f32 accumulation (the
    MXU-native fast path; solves stay f32). resume_y replaces the random
    item-factor init with a [n_items, features] matrix (mid-build
    checkpoint resume: the per-sweep carry is fully determined by Y).

    shard_mesh (mutually exclusive with mesh): run the BUCKETED scan —
    the trainer incremental generations and warm starts use — under pjit
    with the item-factor table sharded by row over the mesh's "model"
    axis (parallel/mesh.model_mesh) and the bucketed lists replicated;
    XLA inserts the gather/scatter collectives. This is the pod-scale
    path for factor tables larger than one chip's HBM that still wants
    the bucketed-width work savings and the donated Y carry, and it
    composes with the warm-start early stop unchanged (train_als_warm
    threads it through).

    timings (single-device path only): pass a dict to receive a
    {"lists_s", "compile_s", "train_s"} breakdown — the XLA compile is
    separated from compute via AOT lower/compile, so benchmarks report
    one-time compilation apart from the per-build cost it amortizes into.
    """
    if mesh is not None and shard_mesh is not None:
        # loud, not silent: a caller combining the two would get
        # mesh-only training with the shard layout dropped — exactly the
        # capability loss sharding exists to prevent (oryxlint's
        # device-placement rule flags such call sites before runtime)
        raise ValueError("train_als: mesh and shard_mesh are mutually exclusive")
    if mesh is not None:
        from oryx_tpu.parallel.mesh import MODEL_AXIS

        if MODEL_AXIS in mesh.shape and mesh.shape[MODEL_AXIS] > 1:
            return train_als_tp(
                data, mesh, features=features, lam=lam, alpha=alpha,
                iterations=iterations, implicit=implicit, cap=cap,
                block=block, seed_key=seed_key, compute_dtype=compute_dtype,
                resume_y=resume_y,
            )
    n_u, n_i = data.n_users, data.n_items
    if n_u == 0 or n_i == 0 or len(data.values) == 0:
        # covers both no-input and everything-deleted-by-NaN-markers
        raise ValueError("empty interaction data")

    if mesh is None:
        import time as _time

        t_mark = _time.perf_counter()
        # single-device: bucketed lists — work scales with real row
        # lengths instead of the heaviest row's power-of-two padding.
        # Row counts round to a 1024 unit so retrains on slowly growing
        # data keep hitting the jit cache.
        unit = 1024
        shard_n = 1
        if shard_mesh is not None:
            from oryx_tpu.parallel.mesh import MODEL_AXIS as _M

            shard_n = int(shard_mesh.shape[_M])
            if shard_n > 1 and unit % shard_n:
                # the sharded row axis must divide evenly across the
                # model axis; non-pow2 shard counts grow the rounding
                # unit instead of failing the device_put
                unit *= shard_n
        u_buckets, blocks_u = _cached_lists(
            "u_buckets", data, (cap, block, unit),
            lambda: build_bucketed_lists(
                data.users, data.items, data.values, n_u, cap,
                block=block, unit=unit,
            ),
        )
        i_buckets, blocks_i = _cached_lists(
            "i_buckets", data, (cap, block, unit),
            lambda: build_bucketed_lists(
                data.items, data.users, data.values, n_i, cap,
                block=block, unit=unit,
            ),
        )
        n_u_pad = -(-n_u // unit) * unit
        n_i_pad = -(-n_i // unit) * unit
        if resume_y is not None:
            y0 = jnp.asarray(_row_pad(np.asarray(resume_y, dtype=np.float32), n_i_pad))
        else:
            key = seed_key if seed_key is not None else RandomManager.get_key()
            # padding rows must be ZERO or phantom items inflate gram(Y)
            # in the first half-iteration
            y0 = (
                jax.random.normal(key, (n_i_pad, features), dtype=jnp.float32) * 0.1
                + 1.0 / math.sqrt(features)
            )
            y0 = y0 * (jnp.arange(n_i_pad) < n_i)[:, None]
        put = jnp.asarray
        if shard_n > 1:
            # pjit-sharded bucketed scan: the item-factor table (the Y
            # carry, donated on warm restarts) lives row-sharded over the
            # mesh's "model" axis; the bucketed lists replicate, and XLA
            # threads the gather/solve/scatter collectives through the
            # SAME compiled scan the single-device path runs
            from oryx_tpu.parallel.mesh import model_sharding, replicated

            rep = replicated(shard_mesh)
            put = lambda a: jax.device_put(jnp.asarray(a), rep)  # noqa: E731
            y0 = jax.device_put(y0, model_sharding(shard_mesh, 2))
        args = (
            tuple(tuple(put(a) for a in b) for b in u_buckets),
            tuple(tuple(put(a) for a in b) for b in i_buckets),
            y0, jnp.float32(lam), jnp.float32(alpha),
        )
        kwargs = dict(
            implicit=implicit, iterations=iterations,
            blocks_u=tuple(blocks_u), blocks_i=tuple(blocks_i), n_u=n_u_pad,
            compute_dtype=compute_dtype,
        )
        # analytic FLOPs of the whole build (dominant einsum terms only —
        # ops/flops.py): benchmarks divide by train_s and the chip peak
        # for an honest MFU figure, and the runtime perf accounting
        # (common/perfstats.py) records the same number per scan call
        from oryx_tpu.ops.flops import als_halfstep_flops

        flops_half_u = sum(
            als_halfstep_flops(b[1].shape[0], b[1].shape[1], features, 0)
            for b in u_buckets
        ) + 2.0 * n_i_pad * features * features
        flops_half_i = sum(
            als_halfstep_flops(b[1].shape[0], b[1].shape[1], features, 0)
            for b in i_buckets
        ) + 2.0 * n_u_pad * features * features
        train_flops = iterations * (flops_half_u + flops_half_i)
        if timings is None:
            # donation is a no-op (with a warning) on CPU; only take the
            # donated program where buffer reuse actually exists
            fn = (
                als_train_bucketed_jit_donated
                if donate_y0 and jax.default_backend() != "cpu"
                else als_train_bucketed_jit
            )
            t_exec = _time.perf_counter()
            x, y = jax.block_until_ready(fn(*args, **kwargs))
            train_s = _time.perf_counter() - t_exec
        else:
            # AOT lower/compile so the one-time XLA compile is measured
            # apart from the compute it amortizes into
            timings["lists_s"] = _time.perf_counter() - t_mark
            timings["train_flops"] = train_flops
            t_mark = _time.perf_counter()
            compiled = als_train_bucketed_jit.lower(*args, **kwargs).compile()
            timings["compile_s"] = _time.perf_counter() - t_mark
            t_mark = _time.perf_counter()
            x, y = jax.block_until_ready(compiled(*args))
            train_s = timings["train_s"] = _time.perf_counter() - t_mark
        _record_train_dispatch(
            args, train_flops, train_s, n_u, n_i, n_u_pad, n_i_pad,
            features, compute_dtype,
        )
        return _finish_model(x, y, n_u, n_i, data)

    # mesh path: one global width, rows padded to a common multiple of the
    # chunk block and the mesh "data" axis so lax.map reshapes and shard
    # layouts both divide evenly
    from oryx_tpu.parallel.mesh import DATA_AXIS, shard_array

    u_lists = _cached_lists(
        "u_lists", data, (cap,),
        lambda: build_padded_lists(data.users, data.items, data.values, n_u, cap),
    )
    i_lists = _cached_lists(
        "i_lists", data, (cap,),
        lambda: build_padded_lists(data.items, data.users, data.values, n_i, cap),
    )

    mesh_n = mesh.shape[DATA_AXIS]
    blk = min(block, 1 << max(0, max(n_u, n_i) - 1).bit_length())
    unit = max(blk, mesh_n) if blk % mesh_n == 0 or mesh_n % blk == 0 else blk * mesh_n
    n_u_pad = -(-n_u // unit) * unit
    n_i_pad = -(-n_i // unit) * unit
    u_idx, u_val, u_mask = (_row_pad(a, n_u_pad) for a in u_lists)
    i_idx, i_val, i_mask = (_row_pad(a, n_i_pad) for a in i_lists)

    if resume_y is not None:
        y0 = jnp.asarray(_row_pad(np.asarray(resume_y, dtype=np.float32), n_i_pad))
    else:
        key = seed_key if seed_key is not None else RandomManager.get_key()
        # small random factors around 1/sqrt(K), the usual ALS init scale;
        # padding rows must be ZERO or phantom items inflate gram(Y) in
        # the first half-iteration
        y0 = (
            jax.random.normal(key, (n_i_pad, features), dtype=jnp.float32) * 0.1
            + 1.0 / math.sqrt(features)
        )
        y0 = y0 * (jnp.arange(n_i_pad) < n_i)[:, None]

    args = [
        shard_array(np.asarray(a), mesh)
        for a in (u_idx, u_val, u_mask, i_idx, i_val, i_mask, y0)
    ]

    x, y = als_train_jit(
        *args,
        jnp.float32(lam),
        jnp.float32(alpha),
        implicit=implicit,
        iterations=iterations,
        block=blk,
        compute_dtype=compute_dtype,
    )
    return _finish_model(x, y, n_u, n_i, data)


def train_als_checkpointed(
    data: InteractionData,
    checkpoint_dir,
    checkpoint_every: int,
    features: int = 10,
    lam: float = 0.001,
    alpha: float = 1.0,
    iterations: int = 10,
    implicit: bool = True,
    mesh=None,
    cap: int = 1024,
    block: int = 1024,
    seed_key=None,
    compute_dtype: str = "float32",
    shard_mesh=None,
) -> ALSModelArrays:
    """train_als with mid-build checkpoints every `checkpoint_every`
    sweeps: a preempted/killed build resumes from the last checkpoint
    instead of restarting, and the resumed run equals the uninterrupted
    one exactly (the per-sweep carry is fully determined by Y, which is
    what gets saved). The spirit of the reference's ALS
    checkpointInterval(5) (ALSUpdate.java:144 breaks RDD lineage every 5
    iterations), re-aimed at the failure mode long TPU builds actually
    have. Checkpoints are atomic (tmp + rename), fingerprinted against
    the exact training configuration, and removed on success.
    """
    import json as _json
    import os
    from pathlib import Path

    if iterations < 1:
        raise ValueError("iterations must be >= 1")
    ck_dir = Path(checkpoint_dir)
    ck_dir.mkdir(parents=True, exist_ok=True)
    ck = ck_dir / "als-train.ckpt.npz"
    import zlib

    # sampled content hash: time-decayed re-aggregation after a crash can
    # produce the same SHAPES with different values; a stale checkpoint
    # must not be accepted against different data
    sample = slice(None, None, max(1, len(data.values) // 262_144))
    data_crc = zlib.crc32(np.ascontiguousarray(data.values[sample]).tobytes())
    data_crc = zlib.crc32(np.ascontiguousarray(data.users[sample]).tobytes(), data_crc)
    data_crc = zlib.crc32(np.ascontiguousarray(data.items[sample]).tobytes(), data_crc)
    fingerprint = _json.dumps(
        {
            "n_users": data.n_users,
            "n_items": data.n_items,
            "nnz": int(len(data.values)),
            "data_crc": data_crc,
            "features": features,
            "lam": float(lam),
            "alpha": float(alpha),
            "implicit": implicit,
            "compute_dtype": compute_dtype,
            "iterations": iterations,
        },
        sort_keys=True,
    )

    done = 0
    resume_y = None
    if ck.exists():
        try:
            with np.load(ck, allow_pickle=False) as z:
                if str(z["fingerprint"]) == fingerprint:
                    done = int(z["done"])
                    resume_y = z["y"]
                    log.info("resuming ALS build from checkpoint: %d/%d sweeps done",
                             done, iterations)
        except Exception:  # noqa: BLE001 - a torn checkpoint means restart
            log.warning("ignoring unreadable ALS checkpoint %s", ck)

    kwargs = dict(
        features=features, lam=lam, alpha=alpha, implicit=implicit,
        mesh=mesh, cap=cap, block=block, compute_dtype=compute_dtype,
        shard_mesh=shard_mesh,
    )
    # checkpoints are only written mid-build (done < iterations) and the
    # fingerprint pins `iterations`, so done < iterations always holds
    # here; clamp defensively anyway — X is derived from Y, so at least
    # one sweep must run
    done = min(done, iterations - 1)
    model = None
    while done < iterations:
        chunk = min(max(1, checkpoint_every), iterations - done)
        model = train_als(
            data, iterations=chunk, seed_key=seed_key,
            resume_y=resume_y, **kwargs,
        )
        done += chunk
        resume_y = model.y
        if done < iterations:
            tmp = str(ck) + ".tmp"
            np.savez(tmp, y=model.y, done=done, fingerprint=fingerprint)
            # np.savez appends .npz to names without it
            os.replace(tmp if os.path.exists(tmp) else tmp + ".npz", ck)
    if ck.exists():
        ck.unlink()
    return model


def train_als_warm(
    data: InteractionData,
    features: int = 10,
    lam: float = 0.001,
    alpha: float = 1.0,
    iterations: int = 10,
    implicit: bool = True,
    mesh=None,
    cap: int = 1024,
    block: int = 1024,
    seed_key=None,
    compute_dtype: str = "float32",
    resume_y: np.ndarray | None = None,
    tol: float = 0.0,
    min_iterations: int = 1,
    check_every: int = 2,
    shard_mesh=None,
) -> tuple[ALSModelArrays, int]:
    """train_als with a convergence-based early stop for warm starts.

    Runs `check_every`-sweep chunks (each re-enters the SAME compiled
    program — the chunk size, not the total, is the jit-cache key, so
    steady-state generations never recompile) and stops once the model's
    PREDICTIONS stop moving: the relative change of x_u·y_i over a fixed
    deterministic sample of observed interactions drops below `tol`.
    Predictions, not factor norms — an ALS factor pair keeps drifting
    along near-degenerate directions (scale/rotation trades between X
    and Y) long after the scores it produces have settled, so a
    Frobenius-on-Y test either never fires or needs a uselessly loose
    threshold. Respects the `min_iterations` floor. A warm resume_y from
    the previous generation typically converges in a fraction of the
    cold iteration count; the per-chunk Y carry is donated to the
    trainer so the chunked loop holds one factor table, not two.
    Returns (model, sweeps actually run).

    tol <= 0 disables the early stop (one full-length train_als call).
    """
    if tol <= 0 or iterations <= max(1, check_every):
        m = train_als(
            data, features=features, lam=lam, alpha=alpha,
            iterations=iterations, implicit=implicit, mesh=mesh, cap=cap,
            block=block, seed_key=seed_key, compute_dtype=compute_dtype,
            resume_y=resume_y, shard_mesh=shard_mesh,
        )
        return m, iterations
    check_every = max(1, check_every)
    # deterministic stride sample of observed pairs (same idiom as the
    # checkpoint fingerprint): cheap, stable across chunks, and scored
    # where the model is actually used
    nnz = len(data.values)
    samp = slice(None, None, max(1, nnz // 4096))
    su, si = data.users[samp], data.items[samp]
    done = 0
    prev_y = resume_y
    prev_pred = None
    model = None
    while done < iterations:
        chunk = min(check_every, iterations - done)
        model = train_als(
            data, features=features, lam=lam, alpha=alpha,
            iterations=chunk, implicit=implicit, mesh=mesh, cap=cap,
            block=block, seed_key=seed_key, compute_dtype=compute_dtype,
            resume_y=prev_y, donate_y0=prev_y is not None,
            shard_mesh=shard_mesh,
        )
        done += chunk
        pred = (model.x[su] * model.y[si]).sum(axis=1)
        if prev_pred is not None:
            denom = float(np.linalg.norm(prev_pred)) or 1.0
            rel = float(np.linalg.norm(pred - prev_pred)) / denom
            if done >= min_iterations and rel < tol:
                log.info(
                    "ALS early stop at sweep %d/%d (relative prediction "
                    "change %.2e < tol %.2e)", done, iterations, rel, tol,
                )
                break
        prev_y, prev_pred = model.y, pred
    return model, done


def _row_pad(a: np.ndarray, n: int) -> np.ndarray:
    if a.shape[0] == n:
        return a
    return np.pad(a, [(0, n - a.shape[0])] + [(0, 0)] * (a.ndim - 1))


# ---------------------------------------------------------------------------
# bucketed lists: rows grouped by interaction count so light rows don't pay
# the heaviest row's padding
# ---------------------------------------------------------------------------

_prepared_lists_cache: dict = {}

# Distinct (data object, list kind, params) entries kept at once. Eviction
# normally rides weakref.finalize when the data object dies; the cap is
# the backstop for non-weakrefable data objects (finalize refuses those)
# and for long-lived processes cycling many live datasets — without it a
# hyperparameter sweep over fresh InteractionData objects grows the cache
# (and the multi-GB padded lists inside it) without bound.
_PREPARED_LISTS_CAP = 16


def _cached_lists(tag: str, data, params: tuple, build):
    """Memoize padded/bucketed list construction per InteractionData object
    (and scalar build parameters). The checkpointed trainer re-enters
    train_als once per chunk with the SAME data object; rebuilding the
    lists each chunk would repeat minutes of host work on large builds.
    Entries die with the data object via weakref.finalize, or with the
    oldest-entry cap for objects finalize can't track."""
    key = (id(data), tag, params)
    hit = _prepared_lists_cache.get(key)
    if hit is not None:
        return hit[0]
    out = build()
    try:
        weakref.ref(data)
        # weakref-able: one finalizer per data object purges all its
        # entries the moment it is collected
        if not any(k[0] == id(data) for k in _prepared_lists_cache):
            weakref.finalize(data, _purge_prepared, id(data))
        pin = None
    except TypeError:
        # data isn't weakref-able (e.g. a slotted/plain-tuple stand-in in
        # tests): cache anyway — but PIN the object in EVERY entry.
        # Untracked, id(data) could be reused by a new object at the same
        # address after this one dies, silently serving another dataset's
        # lists; per-entry pins survive cap eviction of a sibling entry,
        # and the cap bounds what the pins can keep alive.
        pin = data
    while len(_prepared_lists_cache) >= _PREPARED_LISTS_CAP:
        _prepared_lists_cache.pop(next(iter(_prepared_lists_cache)))
    _prepared_lists_cache[key] = (out, pin)
    return out


def _purge_prepared(obj_id: int) -> None:
    for k in [k for k in _prepared_lists_cache if k[0] == obj_id]:
        _prepared_lists_cache.pop(k, None)


def build_bucketed_lists(
    entity: np.ndarray,
    other: np.ndarray,
    values: np.ndarray,
    n_entities: int,
    cap: int = 1024,
    edges: tuple[int, ...] = (128, 512, 1024),
    min_rows: int = 4096,
    block: int = 1024,
    unit: int = 1024,
) -> tuple[list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]], list[int]]:
    """Like build_padded_lists, but rows are grouped into width buckets.

    One global P pads every row to the heaviest row's next power of two —
    at MovieLens-25M shape the mean row is ~150 interactions against
    P=1024, so >6x of the gather traffic and normal-equation FLOPs are
    padding. Here each row lands in the smallest bucket width that holds
    it (capped like before; largest-|value| kept on truncation), so the
    einsum work is proportional to the data, not to the tail.

    Returns (buckets, blocks): per bucket (rows [S] int32 into the entity
    axis, idx [S,P], val [S,P], mask [S,P]) with S padded to a multiple of
    its lax.map block AND of `unit` (so the jit cache keys on rounded
    sizes, not exact row counts; padding rows carry id n_entities —
    scattered with mode='drop'); blocks holds the per-bucket block size,
    capped at the caller's `block` working-set bound. Buckets with fewer
    than min_rows rows merge upward to bound compile variants, and each
    bucket's width clips to its own max row length so merged-up small
    datasets never pad past their data.
    """
    edges_arr = [e for e in edges if e < cap] + [cap]
    counts = np.bincount(entity, minlength=n_entities)
    cape = np.minimum(counts, cap)
    b_of = np.searchsorted(edges_arr, cape)  # smallest edge >= cape
    sizes = np.bincount(b_of, minlength=len(edges_arr))
    for j in range(len(edges_arr) - 1):  # merge small buckets upward
        if 0 < sizes[j] < min_rows:
            sizes[j + 1] += sizes[j]
            sizes[j] = 0
            b_of[b_of == j] = j + 1

    # rank interactions within each row, largest |value| first (truncation
    # keeps the most informative entries — same policy as the flat builder)
    order = np.lexsort((-np.abs(values), entity))
    e, o, v = entity[order], other[order], np.asarray(values)[order]
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    rank = np.arange(len(e)) - np.repeat(starts, counts)
    pe = np.asarray(edges_arr)[b_of]
    keep = rank < pe[e]
    e, o, v, rank = e[keep], o[keep], v[keep], rank[keep]

    buckets = []
    blocks = []
    for j, p_edge in enumerate(edges_arr):
        rows = np.nonzero(b_of == j)[0]
        if rows.size == 0:
            continue
        # clip the width to this bucket's real max row length: an upward
        # merge of a small dataset must not pad everyone to the cap edge
        p_need = int(cape[rows].max()) if rows.size else 1
        p = 1 << max(0, min(int(p_edge), max(p_need, 1)) - 1).bit_length()
        blk = min(block, max(64, (1 << 20) // p))
        blk = 1 << (blk.bit_length() - 1)  # pow2 so it divides the unit
        u = max(blk, unit)  # pow2 >= blk -> multiples of u divide by blk
        s = -(-rows.size // u) * u
        blk = min(blk, s)
        pos_of = np.full(n_entities, -1, dtype=np.int64)
        pos_of[rows] = np.arange(rows.size)
        m = b_of[e] == j
        idx = np.zeros((s, p), dtype=np.int32)
        val = np.zeros((s, p), dtype=np.float32)
        mask = np.zeros((s, p), dtype=np.float32)
        idx[pos_of[e[m]], rank[m]] = o[m]
        val[pos_of[e[m]], rank[m]] = v[m]
        mask[pos_of[e[m]], rank[m]] = 1.0
        rows_padded = np.full(s, n_entities, dtype=np.int32)
        rows_padded[: rows.size] = rows
        buckets.append((rows_padded, idx, val, mask))
        blocks.append(blk)
    return buckets, blocks


def _half_step_buckets(
    factors, gram_f, buckets, lam, alpha, implicit: bool, blocks, n_out: int,
    compute_dtype=jnp.float32,
):
    """Bucketed half-iteration: solve each width class with its own padded
    shape, scatter results into the [n_out, K] factor table."""
    k = factors.shape[1]
    x = jnp.zeros((n_out, k), dtype=jnp.float32)
    for (rows, idx, val, mask), blk in zip(buckets, blocks):
        sol = _half_step(
            factors, gram_f, idx, val, mask, lam, alpha, implicit, blk,
            compute_dtype=compute_dtype,
        )
        x = x.at[rows].set(sol, mode="drop")  # padding rows carry id n_out
    return x


def _als_train_bucketed(
    u_buckets, i_buckets, y0, lam, alpha,
    *, implicit: bool, iterations: int, blocks_u, blocks_i, n_u: int,
    compute_dtype: str = "float32",
):
    """Bucketed ALS training loop (single-device / data-replicated). Same
    math as als_train_jit — the buckets partition exactly the same padded
    lists — with work proportional to real row lengths."""
    cdt = jnp.dtype(compute_dtype)

    def body(carry, _):
        _x_prev, y = carry
        x = _half_step_buckets(
            y, gram(y), u_buckets, lam, alpha, implicit, blocks_u, n_u,
            compute_dtype=cdt,
        )
        y_new = _half_step_buckets(
            x, gram(x), i_buckets, lam, alpha, implicit, blocks_i, y.shape[0],
            compute_dtype=cdt,
        )
        return (x, y_new), None

    x0 = jnp.zeros((n_u, y0.shape[1]), dtype=jnp.float32)
    (x_fin, y_fin), _ = jax.lax.scan(body, (x0, y0), None, length=iterations)
    return x_fin, y_fin


_BUCKETED_STATICS = (
    "implicit", "iterations", "blocks_u", "blocks_i", "n_u", "compute_dtype"
)

als_train_bucketed_jit = partial(jax.jit, static_argnames=_BUCKETED_STATICS)(
    _als_train_bucketed
)

# warm-start variant: the incoming Y carry is DONATED so XLA reuses its
# HBM buffer for the outgoing factors — the early-stop loop re-enters
# this program once per convergence check, and without donation every
# chunk would briefly hold two full item-factor tables
als_train_bucketed_jit_donated = partial(
    jax.jit, static_argnames=_BUCKETED_STATICS, donate_argnums=(2,)
)(_als_train_bucketed)


# ---------------------------------------------------------------------------
# tensor-parallel trainer: factor tables sharded over the mesh
# ---------------------------------------------------------------------------
#
# The data-parallel trainer above replicates both factor tables on every
# device; factor tables bigger than one chip's HBM need true model sharding.
# Design (the TPU-native scaling of the reference's partition-summed Gram,
# PartitionedFeatureVectors.java:209-213):
#
#   X rows sharded over "data" (dp), Y rows sharded over "model" (tp).
#   User half-step: each (d, m) device computes the partial normal-equation
#   terms A_u, b_u for ITS user rows from ITS resident Y block only (masked
#   local gather — items outside the block contribute zero), then A/b are
#   psum'd over "model". Every model replica solves the same [K,K] systems
#   (redundant solves, negligible next to the einsum), so X stays sharded
#   over "data" and replicated over "model" with no extra collective.
#   Item half-step is symmetric with the axes swapped (partials psum'd over
#   "data"). Y is NEVER materialized whole on any device, and the einsum
#   FLOPs split tp ways (user step) / dp ways (item step).

def _half_step_tp(
    factors_local, gram_full, base, idx, val, mask, lam, alpha,
    implicit: bool, block: int, other_axis: str, compute_dtype=jnp.float32,
):
    """One TP half-iteration inside shard_map.

    factors_local: [M_local, K] this device's block of the fixed side.
    base: global row index of factors_local[0].
    idx/val/mask: [B_local, P] padded lists for this device's solving rows,
    with GLOBAL indices into the fixed side.
    """
    n, p = idx.shape
    m_local, k = factors_local.shape
    eye = jnp.eye(k, dtype=jnp.float32)
    nb = n // block
    prec = (
        jax.lax.Precision.DEFAULT
        if compute_dtype == jnp.bfloat16
        else jax.lax.Precision.HIGHEST
    )

    def one_block(args):
        bidx, bval, bmask = args
        rel = bidx - base
        inblk = ((rel >= 0) & (rel < m_local)).astype(jnp.float32) * bmask
        yu = factors_local[jnp.clip(rel, 0, m_local - 1)].astype(compute_dtype)
        if implicit:
            w = alpha * bval * inblk
            a_part = jnp.einsum(
                "bpk,bp,bpl->bkl", yu, w.astype(compute_dtype), yu,
                precision=prec, preferred_element_type=jnp.float32,
            )
            pref = (bval > 0).astype(jnp.float32) * inblk
            b_part = jnp.einsum(
                "bpk,bp->bk", yu, ((1.0 + w) * pref).astype(compute_dtype),
                precision=prec, preferred_element_type=jnp.float32,
            )
        else:
            a_part = jnp.einsum(
                "bpk,bp,bpl->bkl", yu, inblk.astype(compute_dtype), yu,
                precision=prec, preferred_element_type=jnp.float32,
            )
            b_part = jnp.einsum(
                "bpk,bp->bk", yu, (bval * inblk).astype(compute_dtype),
                precision=prec, preferred_element_type=jnp.float32,
            )
        # combine partial normal equations across the fixed side's shards
        a_part = jax.lax.psum(a_part, other_axis)
        b_part = jax.lax.psum(b_part, other_axis)
        if implicit:
            a = gram_full[None] + a_part + lam * eye[None]
        else:
            # n_u from the FULL list (replicated across other_axis), so the
            # ALS-WR regularization matches the unsharded trainer exactly
            n_u = bmask.sum(axis=1)
            a = a_part + (lam * jnp.maximum(n_u, 1.0))[:, None, None] * eye[None]
        chol = jnp.linalg.cholesky(a)
        yb = jax.scipy.linalg.solve_triangular(chol, b_part[..., None], lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(chol, -1, -2), yb, lower=False
        )[..., 0]

    blocks = jax.lax.map(
        one_block,
        (
            idx.reshape(nb, block, p),
            val.reshape(nb, block, p),
            mask.reshape(nb, block, p),
        ),
    )
    return blocks.reshape(n, k)


@lru_cache(maxsize=16)
def als_train_tp_jit(
    mesh, *, implicit: bool, iterations: int, block: int,
    compute_dtype: str = "float32",
):
    """Build the jitted tensor-parallel training step over `mesh` (cached
    per (mesh, statics) — the batch layer retrains every generation and
    must hit the jit cache, not recompile).

    Inputs (global shapes): u_* [N_u, P] with N_u % (dp*block) == 0,
    i_* [N_i, P] with N_i % (tp*block) == 0, y0 [N_i, K]. Returns (x, y)
    with x sharded over "data" rows and y over "model" rows.
    """
    from jax.sharding import PartitionSpec as P
    from oryx_tpu.parallel.mesh import (
        DATA_AXIS, MODEL_AXIS, pcast_varying_compat, shard_map_compat,
    )

    cdt = jnp.dtype(compute_dtype)

    def body(u_idx, u_val, u_mask, i_idx, i_val, i_mask, y0, lam, alpha):
        m_i_local = y0.shape[0]  # N_i / tp
        n_u_local = u_idx.shape[0]  # N_u / dp
        y_base = jax.lax.axis_index(MODEL_AXIS) * m_i_local
        x_base = jax.lax.axis_index(DATA_AXIS) * n_u_local

        def one_iter(carry, _):
            _, y_local = carry
            gram_y = jax.lax.psum(gram(y_local), MODEL_AXIS)
            x_local = _half_step_tp(
                y_local, gram_y, y_base, u_idx, u_val, u_mask,
                lam, alpha, implicit, block, MODEL_AXIS, compute_dtype=cdt,
            )
            gram_x = jax.lax.psum(gram(x_local), DATA_AXIS)
            y_local = _half_step_tp(
                x_local, gram_x, x_base, i_idx, i_val, i_mask,
                lam, alpha, implicit, block, DATA_AXIS, compute_dtype=cdt,
            )
            return (x_local, y_local), None

        x0 = jnp.zeros((n_u_local, y0.shape[1]), dtype=jnp.float32)
        # mark the zero-filled carry as device-varying over "data" so its
        # type matches the per-shard x the loop produces (shard_map VMA)
        x0 = pcast_varying_compat(x0, (DATA_AXIS,))
        (x_fin, y_fin), _ = jax.lax.scan(
            one_iter, (x0, y0), None, length=iterations
        )
        return x_fin, y_fin

    row_d = P(DATA_AXIS, None)
    row_m = P(MODEL_AXIS, None)
    return jax.jit(
        shard_map_compat(
            body,
            mesh=mesh,
            in_specs=(row_d, row_d, row_d, row_m, row_m, row_m, row_m, P(), P()),
            out_specs=(row_d, row_m),
            check_vma=False,
        )
    )


def train_als_tp(
    data: InteractionData,
    mesh,
    features: int = 10,
    lam: float = 0.001,
    alpha: float = 1.0,
    iterations: int = 10,
    implicit: bool = True,
    cap: int = 1024,
    block: int = 1024,
    seed_key=None,
    compute_dtype: str = "float32",
    resume_y: np.ndarray | None = None,
) -> ALSModelArrays:
    """Tensor-parallel train_als: X sharded by user over "data", Y by item
    over "model"; neither factor table is ever whole on one device."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from oryx_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    n_u, n_i = data.n_users, data.n_items
    if n_u == 0 or n_i == 0 or len(data.values) == 0:
        raise ValueError("empty interaction data")
    dp, tp = mesh.shape[DATA_AXIS], mesh.shape[MODEL_AXIS]

    u_lists = _cached_lists(
        "u_lists", data, (cap,),
        lambda: build_padded_lists(data.users, data.items, data.values, n_u, cap),
    )
    i_lists = _cached_lists(
        "i_lists", data, (cap,),
        lambda: build_padded_lists(data.items, data.users, data.values, n_i, cap),
    )

    # local row counts must divide the lax.map block: shrink the block to
    # the local shard size when shards are small
    blk_u = min(block, 1 << max(0, (max(1, n_u // dp)) - 1).bit_length())
    blk_i = min(block, 1 << max(0, (max(1, n_i // tp)) - 1).bit_length())
    blk = min(blk_u, blk_i)
    n_u_pad = -(-n_u // (dp * blk)) * (dp * blk)
    n_i_pad = -(-n_i // (tp * blk)) * (tp * blk)
    u_idx, u_val, u_mask = (_row_pad(a, n_u_pad) for a in u_lists)
    i_idx, i_val, i_mask = (_row_pad(a, n_i_pad) for a in i_lists)

    if resume_y is not None:
        y0 = jnp.asarray(_row_pad(np.asarray(resume_y, dtype=np.float32), n_i_pad))
    else:
        key = seed_key if seed_key is not None else RandomManager.get_key()
        if jax.process_count() > 1 and seed_key is None:
            from oryx_tpu.parallel.submesh import current_candidate_mesh

            if current_candidate_mesh() is None:
                # every host must init the SAME y0: its sharding replicates
                # along the cross-host data axis, and per-process urandom-
                # seeded keys would stitch divergent replicas into a
                # silently corrupt model
                from jax.experimental import multihost_utils

                key = jax.random.wrap_key_data(
                    multihost_utils.broadcast_one_to_all(jax.random.key_data(key))
                )
            # else: partitioned pod candidate search — the mesh spans only
            # THIS group's processes, so the pod-wide broadcast would
            # block on groups busy training other candidates. Group-wide
            # key agreement comes from the per-candidate deterministic
            # seed MLUpdate installs before every pod build.
        y0 = (
            jax.random.normal(key, (n_i_pad, features), dtype=jnp.float32) * 0.1
            + 1.0 / math.sqrt(features)
        )
        y0 = y0 * (jnp.arange(n_i_pad) < n_i)[:, None]

    row_d = NamedSharding(mesh, P(DATA_AXIS, None))
    row_m = NamedSharding(mesh, P(MODEL_AXIS, None))
    # spanning-THIS-mesh, not process_count: during a partitioned pod
    # candidate search the mesh covers only this group's processes, and a
    # fully-local sub-mesh must not enter pod-WIDE collectives — two groups'
    # process_allgathers would pair up and stitch different candidates'
    # factors into one corrupt model
    multihost = len({d.process_index for d in mesh.devices.ravel()}) > 1

    def put(a, s):
        # single-process: plain device_put. Multi-host: every process holds
        # the same full host array (the bus delivers the same generation to
        # each), so each process hands jax just its addressable shards.
        if not multihost:
            return jax.device_put(jnp.asarray(a), s)
        a = np.asarray(a)
        return jax.make_array_from_callback(a.shape, s, lambda idx: a[idx])

    step = als_train_tp_jit(
        mesh, implicit=implicit, iterations=iterations, block=blk,
        compute_dtype=compute_dtype,
    )
    x, y = step(
        put(u_idx, row_d), put(u_val, row_d), put(u_mask, row_d),
        put(i_idx, row_m), put(i_val, row_m), put(i_mask, row_m),
        put(y0, row_m), jnp.float32(lam), jnp.float32(alpha),
    )
    if multihost:
        # factor tables come back to every host (each publishes/serves the
        # whole model, like every reference layer holds the full model).
        # Gather WITHIN the mesh — an XLA all-gather over exactly the
        # mesh's devices — never a pod-wide process_allgather: during a
        # partitioned candidate search other process groups are busy
        # training different candidates, and a global collective would
        # pair up across groups and interleave their models
        from jax.sharding import NamedSharding

        rep = NamedSharding(mesh, P(None, None))
        x, y = jax.jit(lambda a, b: (a, b), out_shardings=(rep, rep))(x, y)
        x = np.asarray(x.addressable_data(0))
        y = np.asarray(y.addressable_data(0))
    return _finish_model(
        x, y, n_u, n_i, data
    )


# ---------------------------------------------------------------------------
# incremental fold-in (speed layer + anonymous serving estimates)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("implicit",))
def compute_target_qui(value, current, *, implicit: bool):
    """Target predicted-strength after an interaction of `value`.

    Implicit: interpolate from the current prediction toward 1 (positive
    value) or 0 (negative), fraction value/(1+value); NaN means "no change
    needed" (already out of range). Explicit: the value itself.
    Parity: ALSUtils.computeTargetQui (…/als/ALSUtils.java:37-60).
    """
    if not implicit:
        return value
    pos = (value > 0.0) & (current < 1.0)
    neg = (value < 0.0) & (current > 0.0)
    up = current + (value / (1.0 + value)) * (1.0 - jnp.maximum(0.0, current))
    dn = current + (value / (value - 1.0)) * (-jnp.minimum(1.0, current))
    return jnp.where(pos, up, jnp.where(neg, dn, jnp.nan))


@partial(jax.jit, static_argnames=("implicit",))
def compute_updated_xu(chol, value, xu, yi, *, implicit: bool):
    """Fold one interaction into a user vector: solve (Y'Y) dXu = dQui.Yi
    against the cached Cholesky factor of Y'Y and add the delta.

    xu may be a zero vector with had_xu=False semantics folded in by the
    caller passing current=0.5 sentinel: here, a NaN target yields xu
    unchanged (and callers treat all-zero xu as "new user").
    Parity: ALSUtils.computeUpdatedXu (…/als/ALSUtils.java:74-106).
    vmap over leading dims for micro-batch fold-in.
    """
    had_xu = jnp.any(xu != 0.0)
    qui = jnp.where(had_xu, jnp.vdot(xu, yi), 0.0)
    current = jnp.where(had_xu, qui, 0.5)
    target = compute_target_qui(value, current, implicit=implicit)
    dqui = jnp.where(jnp.isnan(target), 0.0, target - qui)
    rhs = (dqui * yi)[:, None]
    y = jax.scipy.linalg.solve_triangular(chol, rhs, lower=True)
    dxu = jax.scipy.linalg.solve_triangular(chol.T, y, lower=False)[:, 0]
    return xu + dxu


fold_in_batch = jax.vmap(
    lambda chol, value, xu, yi: compute_updated_xu(chol, value, xu, yi, implicit=True),
    in_axes=(None, 0, 0, 0),
)

fold_in_batch_explicit = jax.vmap(
    lambda chol, value, xu, yi: compute_updated_xu(chol, value, xu, yi, implicit=False),
    in_axes=(None, 0, 0, 0),
)


# ---------------------------------------------------------------------------
# scoring
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("k",))
def topk_dot(xu, y, *, k: int, exclude_mask=None):
    """Scores = Y.xu ; top-k with optional exclusion mask. One matmul +
    lax.top_k on device — this is the whole serving hot path that the
    reference needed LSH partitions and thread fan-out for
    (ALSServingModel.topN, …/als/model/ALSServingModel.java:264-279)."""
    scores = y.astype(jnp.float32) @ xu.astype(jnp.float32)
    if exclude_mask is not None:
        scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("k",))
def topk_dot_batch_xla(xs, y, *, k: int):
    """Batched variant: [B,K] users at once -> one [B,I] matmul. The XLA
    form materializes the [B,I] score matrix in HBM; at serving scale the
    fused Pallas kernel (ops/pallas_topk.py) avoids that round-trip."""
    scores = xs.astype(jnp.float32) @ y.astype(jnp.float32).T
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("k", "recall"))
def topk_dot_batch_approx(xs, y, *, k: int, recall: float):
    """Batched APPROXIMATE top-k via the TPU-native partial-reduce
    (jax.lax.approx_max_k, measured 9.5x the exact fused kernel at
    4096 x 1M x 50). The on-device replacement for the reference's LSH
    candidate subsampling: recall is a compiler-verified target instead
    of an emergent property of hash partitions, and the serving tier's
    exact f32 re-rank runs on whatever comes back either way. On
    non-TPU backends approx_max_k computes exactly."""
    scores = jnp.dot(
        xs, y.T, preferred_element_type=jnp.float32
    )
    return jax.lax.approx_max_k(scores, k, recall_target=recall)


@partial(jax.jit, static_argnames=("k", "recall"))
def topk_dot_batch_quant_xla(xs, q, scale, *, k: int, recall: float = 1.0):
    """Batched top-k over an int8-quantized item matrix (q [I,F] int8,
    scale [I] f32). Queries quantize per-row exactly like the Pallas
    int8 kernel (ops/pallas_topk.py quantize_queries), and the dot runs
    over the quantized values in f32 — int8 x int8 products summed over
    a lane tile stay < 2^24, so the f32 accumulation is EXACT and this
    is a bit-faithful reference for the kernel's int32 MXU path. Scales
    multiply back in the same order the kernel applies them. The XLA
    reference the Pallas quantized kernel is tested against, and the CPU
    path for score-mode=quantized; the serving tier's exact f32 re-rank
    of the returned candidates corrects in-candidate ordering either
    way."""
    from oryx_tpu.ops.pallas_topk import quantize_queries

    xq, sx = quantize_queries(xs)
    scores = jnp.dot(
        xq.astype(jnp.float32), q.T.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale[None, :] * sx[:, None]
    if recall < 1.0:
        return jax.lax.approx_max_k(scores, k, recall_target=recall)
    return jax.lax.top_k(scores, k)


_pallas_failed_shapes: set = set()

# Largest k dispatched to the fused Pallas kernel. The serving
# micro-batcher derives a k bucket from this so default /recommend
# overfetch (k=18) stays on the fused path — keep them coupled. The
# gen-2 bitonic kernel maintains a full 128-lane running top-k whatever
# the k, so the bound is the lane tile itself (the gen-1 argmax-round
# kernel capped out at 32, pushing the 128 bucket to the XLA fallback).
PALLAS_TOPK_MAX_K = 128


def topk_dot_batch_chunked(xs, y_chunks, *, k: int, recall: float = 1.0):
    """Exact batched top-k over an item matrix supplied as row CHUNKS:
    per-chunk top-k with the normal kernel (every equal-shaped chunk hits
    the SAME compiled program), then one merge over the C*k candidates
    with indices rebased to global rows.

    Why: a single (20M, 250) bf16 dispatch is a 10 GB operand whose
    one-shot compile crashed the remote-compile helper in the round-5
    window (BENCH_TPU_WINDOW_r05.json scaling row error); bounded chunk
    shapes keep every compiled program small and reusable. Top-k is
    associative over row partitions, so the merge is exact; with
    recall < 1 each chunk's partial reduce carries the same per-chunk
    recall target."""
    total = sum(int(y.shape[0]) for y in y_chunks)
    if k > total:
        # contract parity with the single-dispatch kernel (lax.top_k
        # raises there); padded merge slots would otherwise fabricate
        # (-inf, aliased-index) results
        raise ValueError(f"k={k} exceeds total rows {total}")
    vals, idxs = [], []
    base = 0
    for y in y_chunks:
        v, i = topk_dot_batch(xs, y, k=min(k, y.shape[0]), recall=recall)
        pad = k - v.shape[1]
        if pad > 0:  # a chunk smaller than k still merges cleanly
            v = jnp.pad(v, ((0, 0), (0, pad)), constant_values=-jnp.inf)
            i = jnp.pad(i, ((0, 0), (0, pad)))
        vals.append(v)
        idxs.append(i + base)
        base += y.shape[0]
    cat_v = jnp.concatenate(vals, axis=1)
    cat_i = jnp.concatenate(idxs, axis=1)
    best_v, pos = jax.lax.top_k(cat_v, min(k, cat_v.shape[1]))
    return best_v, jnp.take_along_axis(cat_i, pos, axis=1)


def topk_dot_batch(xs, y, *, k: int, recall: float = 1.0):
    """Batched top-k scoring with automatic kernel selection: recall < 1
    takes the approximate partial-reduce; exact requests take the fused
    streaming Pallas kernel on TPU (gen-2 bitonic-merge kernel,
    ops/pallas_topk.py — exact index agreement with lax.top_k up to
    k=128, never materializes the [B,I] scores), plain XLA elsewhere. A
    QuantizedMatrix (int8 rows + per-row scales, score-mode=quantized)
    dispatches the quantized kernel on TPU and the dequantize-and-dot XLA
    form elsewhere; a ChunkedMatrix (oversized model, ops/transfer.py)
    routes through the chunk-and-merge form; a ShardedMatrix (pod-scale
    row shards, one device per shard) scores per shard — each shard
    re-entering this selection with its own dtype — and merges the
    partials with the cross-shard bitonic merge (ops/shard_topk.py),
    bit-identical to the unsharded dispatch. A kernel failure only
    disables that exact (shapes, k) signature — standard serving shapes
    keep the fast path."""
    from oryx_tpu.ops.transfer import (
        ChunkedMatrix, QuantizedMatrix, ShardedMatrix,
    )

    if isinstance(y, ShardedMatrix):
        from oryx_tpu.ops.shard_topk import topk_dot_batch_sharded

        return topk_dot_batch_sharded(xs, y, k=k, recall=recall)
    if isinstance(y, ChunkedMatrix):
        return topk_dot_batch_chunked(xs, y.chunks, k=k, recall=recall)
    if isinstance(y, QuantizedMatrix):
        n_items = y.shape[0]
        sig = (xs.shape, y.shape, xs.dtype, "int8", k)
        if (
            recall >= 1.0
            and k <= PALLAS_TOPK_MAX_K
            and n_items >= 32768
            and sig not in _pallas_failed_shapes
            and jax.default_backend() == "tpu"
        ):
            from oryx_tpu.ops.pallas_topk import topk_dot_batch_pallas

            try:
                return topk_dot_batch_pallas(xs, y.q, scales=y.scale, k=k)
            except Exception:  # noqa: BLE001 - e.g. VMEM overflow
                log.exception(
                    "pallas quantized top-k failed for %s; falling back to XLA",
                    sig,
                )
                _pallas_failed_shapes.add(sig)
        return topk_dot_batch_quant_xla(
            xs, y.q, y.scale, k=k, recall=float(recall) if recall < 1.0 else 1.0
        )
    n_items = y.shape[0]
    if xs.dtype != y.dtype:
        # mixed-precision queries score in the matrix's dtype (the bf16
        # serving view); accumulation is f32 either way
        xs = jnp.asarray(xs, dtype=y.dtype)
    if recall < 1.0:
        return topk_dot_batch_approx(xs, y, k=k, recall=float(recall))
    sig = (xs.shape, y.shape, xs.dtype, y.dtype, k)
    if (
        k <= PALLAS_TOPK_MAX_K
        and n_items >= 32768
        and sig not in _pallas_failed_shapes
        and jax.default_backend() == "tpu"
    ):
        from oryx_tpu.ops.pallas_topk import topk_dot_batch_pallas

        try:
            return topk_dot_batch_pallas(xs, y, k=k)
        except Exception:  # noqa: BLE001 - e.g. VMEM overflow on odd shapes
            log.exception("pallas top-k kernel failed for %s; falling back to XLA", sig)
            _pallas_failed_shapes.add(sig)
    return topk_dot_batch_xla(xs, y, k=k)
