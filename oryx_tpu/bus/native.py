"""ctypes bindings for the native oryxbus appender/scanner (liboryxbus.so).

Built from native/oryxbus/oryxbus.cpp (`make` there). When present, the
file-log broker routes appends and index scans through it; the pure-Python
paths in filelog.py remain the fallback so the framework runs unbuilt.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path

import numpy as np

_LIB_NAMES = ("liboryxbus.so",)


_build_attempted = False


def _find_lib() -> str | None:
    env = os.environ.get("ORYXBUS_LIB")
    if env and Path(env).exists():
        return env
    here = Path(__file__).resolve()
    candidates = [
        here.parent,
        here.parent.parent.parent / "native" / "oryxbus",
    ]
    for d in candidates:
        for n in _LIB_NAMES:
            p = d / n
            if p.exists():
                return str(p)
    return _maybe_build()


def _maybe_build() -> str | None:
    """Compile the library in place on first use when a toolchain exists —
    a fresh checkout should get the native fast paths without a manual
    build step. One attempt per process; failure leaves the Python
    fallbacks in charge."""
    global _build_attempted
    if _build_attempted:
        return None
    _build_attempted = True
    src_dir = Path(__file__).resolve().parent.parent.parent / "native" / "oryxbus"
    src = src_dir / "oryxbus.cpp"
    if not src.exists():
        return None
    import shutil
    import subprocess
    import tempfile

    out = src_dir / "liboryxbus.so"
    # build to a temp name then atomic-rename: concurrent processes (the
    # multi-process e2e spawns several at once) must never dlopen a
    # half-written .so. The Makefile stays the single source of truth for
    # flags; `SO=` points its output at the temp name.
    tmp = None
    try:
        make = shutil.which("make")
        gxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
        if make is None and gxx is None:
            return None
        with tempfile.NamedTemporaryFile(
            dir=src_dir, suffix=".so.tmp", delete=False
        ) as tf:
            tmp = tf.name
        # the reservation file must not exist when make runs — an empty
        # up-to-date target would make it a no-op; the unique NAME is the
        # concurrency guard, not the inode
        os.unlink(tmp)
        # WARNFLAGS without -Werror: this OPPORTUNISTIC import-time build
        # runs on arbitrary operator toolchains, where a future compiler's
        # new -Wall diagnostic must degrade to the Python fallback loudly
        # below — not silently lose the native bus. The Makefile's default
        # keeps -Werror for explicit/CI/sanitize builds, where a human
        # sees the failure.
        if make is not None and (src_dir / "Makefile").exists():
            cmd = [make, "-C", str(src_dir), f"SO={os.path.basename(tmp)}",
                   "WARNFLAGS=-Wall -Wextra"]
        else:
            cmd = [gxx, "-O2", "-Wall", "-Wextra", "-fPIC",
                   "-std=c++17", "-shared", "-o", tmp, str(src)]
        # never inherit the sanitizer switch here: an ASan-instrumented
        # auto-build cannot dlopen into this (uninstrumented) process —
        # the sanitized library is built explicitly by the slow test /
        # `make sanitize` under its own name and LD_PRELOAD
        env = {k: v for k, v in os.environ.items() if k != "ORYX_NATIVE_SANITIZE"}
        proc = subprocess.run(cmd, capture_output=True, timeout=120, env=env)
        if (
            proc.returncode != 0
            or not os.path.exists(tmp)
            or not os.path.getsize(tmp)
        ):
            import logging

            logging.getLogger(__name__).warning(
                "native oryxbus auto-build failed (rc=%s); using the "
                "pure-Python bus paths. stderr tail: %s",
                proc.returncode,
                proc.stderr.decode("utf-8", "replace")[-500:],
            )
            if os.path.exists(tmp):
                os.unlink(tmp)
            return None
        os.replace(tmp, out)
        return str(out)
    except Exception:  # noqa: BLE001 - any build problem means "no native"
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return None


class NativeAppender:
    _instance: "NativeAppender | None" = None

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.oryxbus_append.restype = ctypes.c_int
        lib.oryxbus_append.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int32,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.oryxbus_append_batch.restype = ctypes.c_int
        lib.oryxbus_append_batch.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
        lib.oryxbus_scan.restype = ctypes.c_int64
        lib.oryxbus_scan.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.oryxbus_parse_interactions.restype = ctypes.c_int64
        lib.oryxbus_parse_interactions.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
        ]

    @classmethod
    def load(cls) -> "NativeAppender":
        if cls._instance is None:
            path = _find_lib()
            if path is None:
                raise FileNotFoundError("liboryxbus.so not built")
            cls._instance = cls(ctypes.CDLL(path))
        return cls._instance

    def append(self, path: str, key: str | None, message: str) -> None:
        kb = key.encode("utf-8") if key is not None else None
        mb = message.encode("utf-8")
        rc = self._lib.oryxbus_append(
            path.encode(), kb, len(kb) if kb else 0, mb, len(mb)
        )
        if rc != 0:
            raise OSError(-rc, f"oryxbus_append failed for {path}")

    def append_batch(self, path: str, encoded: bytes) -> None:
        rc = self._lib.oryxbus_append_batch(path.encode(), encoded, len(encoded))
        if rc != 0:
            raise OSError(-rc, f"oryxbus_append_batch failed for {path}")

    def scan(self, path: str, start_pos: int, max_records: int | None = None) -> tuple[np.ndarray, int]:
        """Record byte positions from start_pos + final scanned-to position.
        The buffer is sized from the unscanned byte span (a record is >= 8
        bytes) so tail-polling a busy log doesn't allocate megabytes per
        refresh; loops if the file grew beyond the estimate mid-scan."""
        positions: list[int] = []
        pos = start_pos
        while True:
            if max_records is None:
                try:
                    span = max(0, os.stat(path).st_size - pos)
                except OSError:
                    span = 0
                cap = max(16, span // 8 + 1)
            else:
                cap = max_records
            buf = (ctypes.c_int64 * cap)()
            scanned = ctypes.c_int64(pos)
            n = self._lib.oryxbus_scan(path.encode(), pos, buf, cap, ctypes.byref(scanned))
            if n < 0:
                raise OSError(-n, f"oryxbus_scan failed for {path}")
            positions.extend(buf[:n])
            pos = scanned.value
            if max_records is not None or n < cap:
                break
        return np.asarray(positions, dtype=np.int64), pos

    def parse_interactions(
        self, buf: bytes
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Native CSV data loader: newline-separated "user,item[,value[,ts]]"
        bytes -> (users i64, items i64, values f64, timestamps i64, ok u8)
        with no Python object per record. ok=0 rows need the Python parser
        (JSON-array lines, quoted CSV, non-canonical integer ids)."""
        cap = buf.count(b"\n") + 1
        users = np.empty(cap, dtype=np.int64)
        items = np.empty(cap, dtype=np.int64)
        vals = np.empty(cap, dtype=np.float64)
        tss = np.empty(cap, dtype=np.int64)
        ok = np.empty(cap, dtype=np.uint8)
        n = self._lib.oryxbus_parse_interactions(
            buf,
            len(buf),
            users.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            items.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            tss.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            cap,
        )
        if n < 0:
            raise OSError(-n, "oryxbus_parse_interactions failed")
        return users[:n], items[:n], vals[:n], tss[:n], ok[:n]
