#!/usr/bin/env python3
"""Run the three nightly quality gates and write a committed artifact.

Round-4 verdict #7: the env-gated nightly gates only ran when someone
remembered to run them, and their calibration evidence lived in
docstrings. This runner executes the SAME harness configurations as
tests/test_quality_gate.py's ORYX_NIGHTLY gates — the 25M-shape bf16 ALS
NaN-guard gate, the covertype-shape RDF accuracy floor, and the planted-
blob k-means floors — and records the numbers with timestamps in
QUALITY_r{N}.json so quality claims carry the same provenance discipline
as perf claims.

    python tools/quality_nightly.py [round_number]

Exit 0 only if every gate is green.
"""

from __future__ import annotations

import json
import os
import sys
import time
from datetime import datetime, timezone
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _live_sampler_fields(
    n_items: int = 20_000, features: int = 50, n_queries: int = 64
) -> dict:
    """Drive the RUNTIME shadow-rescore sampler (common/qualitystats.py)
    through a real quantized ALSServingModel — the same request path
    production samples — and report its windowed recall. This is what
    makes the nightly artifact and the live oryx_live_recall_at_k gauge
    one vocabulary: both numbers come out of the identical sampler code
    on the identical serve pipeline."""
    import numpy as np

    from oryx_tpu.common.config import load_config
    from oryx_tpu.common.qualitystats import QualityStats
    from oryx_tpu.apps.als.serving import ALSServingModel
    from oryx_tpu.apps.als.state import ALSState

    cfg = load_config(overlay={
        "oryx.monitoring.quality.sample-rate": 1.0,
        "oryx.monitoring.quality.window-sec": 600,
        "oryx.monitoring.quality.max-queue": max(256, n_queries),
    })
    rng = np.random.default_rng(29)
    state = ALSState(features, implicit=True)
    ids = [f"i{j}" for j in range(n_items)]
    state.y.bulk_set(ids, rng.standard_normal((n_items, features)).astype(np.float32))
    state.set_expected([], ids)
    model = ALSServingModel(state, score_mode="quantized")
    qs = QualityStats()
    qs.configure(cfg)
    # route this model's shadow samples into the PRIVATE tracker so the
    # nightly number never mixes with the process-global window
    import oryx_tpu.common.qualitystats as _qmod

    prev = _qmod._default
    _qmod._default = qs
    try:
        for _ in range(n_queries):
            q = rng.standard_normal(features).astype(np.float32)
            model.top_n(q, 10)
        qs.flush(60)
    finally:
        _qmod._default = prev
        model.close()
        qs.close()
    live = qs.live_recall()
    return {
        "live_recall_at_10": round(live, 4) if live == live else None,
        "live_recall_samples": qs.samples_processed(),
    }


def main() -> int:
    round_no = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    out_path = Path(__file__).resolve().parent.parent / (
        f"QUALITY_r{round_no:02d}.json" if round_no else "QUALITY.json"
    )

    from oryx_tpu.common.rng import RandomManager
    from oryx_tpu.ml.quality import MIN_SCORE_MODE_RECALL
    from tests.test_quality_gate import (
        AUC_FLOOR,
        KMEANS_SIL_FLOOR,
        KMEANS_SSE_RATIO_CEIL,
        ML25M_SHAPE,
        RDF_ACC_FLOOR,
        SEQ_HIT_RATE_FLOOR,
    )

    import jax

    doc: dict = {
        "started_at": datetime.now(timezone.utc).isoformat(timespec="seconds"),
        "platform": jax.devices()[0].platform,
        "floors": {
            "als_auc": AUC_FLOOR,
            "als_nan_rows": 0,
            "rdf_accuracy": RDF_ACC_FLOOR,
            "kmeans_sse_ratio_max": KMEANS_SSE_RATIO_CEIL,
            "kmeans_silhouette": KMEANS_SIL_FLOOR,
            "score_mode_recall_at_10": MIN_SCORE_MODE_RECALL,
            "seq_hit_rate_at_10": SEQ_HIT_RATE_FLOOR,
        },
        "gates": {},
    }
    ok = True

    def record(name: str, fields: dict, green: bool) -> None:
        nonlocal ok
        ok = ok and green
        fields["green"] = green
        fields["finished_at"] = datetime.now(timezone.utc).isoformat(
            timespec="seconds"
        )
        doc["gates"][name] = fields
        out_path.write_text(json.dumps(doc, indent=1))
        print(f"{name}: {'GREEN' if green else 'RED'} {fields}", flush=True)

    # ---- gate 1: 25M-shape bf16 ALS NaN guard + AUC floor ---------------
    from oryx_tpu.ml.quality import (
        build_and_evaluate,
        build_and_evaluate_kmeans,
        build_and_evaluate_rdf,
        evaluate_score_mode_recall,
    )

    t0 = time.perf_counter()
    rep = build_and_evaluate(
        **ML25M_SHAPE, features=50, iterations=3,
        compute_dtype="bfloat16", seed=7,
    )
    record(
        "als_25m_bf16",
        {
            "auc": round(rep.auc, 4),
            "nan_rows": rep.nan_rows,
            "interactions": rep.interactions,
            "build_s": round(rep.build_s, 1),
            "wall_s": round(time.perf_counter() - t0, 1),
        },
        rep.nan_rows == 0 and rep.auc >= AUC_FLOOR,
    )

    # ---- gate 2: covertype-shape RDF accuracy floor ---------------------
    RandomManager.use_test_seed(1)
    t0 = time.perf_counter()
    rdf = build_and_evaluate_rdf(num_trees=10)
    record(
        "rdf_covertype_shape",
        {
            "accuracy": round(rdf.accuracy, 4),
            "accuracy_ceiling": round(rdf.accuracy_ceiling, 4),
            "examples": rdf.examples,
            "trees": rdf.trees,
            "build_s": round(rdf.build_s, 1),
            "wall_s": round(time.perf_counter() - t0, 1),
        },
        rdf.accuracy >= RDF_ACC_FLOOR,
    )

    # ---- gate 3: planted-blob k-means floors ----------------------------
    RandomManager.use_test_seed(1)
    t0 = time.perf_counter()
    km = build_and_evaluate_kmeans(
        n_points=1_000_000, dims=20, k=50, iterations=10
    )
    record(
        "kmeans_planted_blobs",
        {
            "sse_ratio": round(km.sse_ratio, 4),
            "silhouette": round(km.silhouette, 3),
            "points": km.points,
            "k": km.k,
            "build_s": round(km.build_s, 1),
            "wall_s": round(time.perf_counter() - t0, 1),
        },
        km.sse_ratio <= KMEANS_SSE_RATIO_CEIL
        and km.silhouette >= KMEANS_SIL_FLOOR,
    )

    # ---- gate 4: serving score-mode recall floor ------------------------
    # speed modes can never silently buy wrong answers: quantized (int8 +
    # exact rescore) and approx (partial reduce) must hold recall@10
    # against the exact top-k on the standing corpus
    RandomManager.use_test_seed(1)
    t0 = time.perf_counter()
    rr = evaluate_score_mode_recall()
    live = _live_sampler_fields()
    record(
        "score_mode_recall",
        {
            # _rescored suffix: these measure the full serve pipeline
            # (overfetch + exact f32 re-rank). bench.py's
            # approx_recall_at_10/quantized_recall_at_10 are the RAW
            # kernel selections at k — same helper, different pipeline;
            # the names differ so the two artifacts can't be conflated
            "approx_recall_at_10_rescored": round(rr.recall_approx, 4),
            "quantized_recall_at_10_rescored": round(rr.recall_quantized, 4),
            "k": rr.k,
            "n_items": rr.n_items,
            "n_queries": rr.n_queries,
            "approx_recall_target": rr.approx_recall_target,
            # the RUNTIME sampler's numbers on the same class of corpus:
            # nightly and production share one recall vocabulary
            # (oryx_live_recall_at_k == live_recall_at_10 here and in
            # bench's http stage), so a nightly regression and a live
            # pager fire on the same definition
            **live,
            "wall_s": round(time.perf_counter() - t0, 1),
        },
        rr.green,
    )

    # ---- gate 5: seq next-item hit-rate floor ---------------------------
    # the fourth packaged app is recall-gated like the ALS score modes:
    # planted-successor sessions, hit-rate@10 on held-out final
    # transitions (ceiling ~0.85 at follow_p=0.85, chance k/V)
    RandomManager.use_test_seed(1)
    t0 = time.perf_counter()
    from oryx_tpu.ml.quality import build_and_evaluate_seq

    sq = build_and_evaluate_seq()
    record(
        "seq_next_item",
        {
            "hit_rate_at_10": round(sq.hit_rate, 4),
            "chance": round(sq.chance, 4),
            "examples": sq.examples,
            "n_items": sq.n_items,
            "n_sessions": sq.n_sessions,
            "epochs_run": sq.epochs_run,
            "build_s": round(sq.build_s, 1),
            "wall_s": round(time.perf_counter() - t0, 1),
        },
        sq.hit_rate >= SEQ_HIT_RATE_FLOOR,
    )

    doc["all_green"] = ok
    out_path.write_text(json.dumps(doc, indent=1))
    print(f"wrote {out_path} all_green={ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    os.environ.setdefault("ORYX_NIGHTLY", "1")
    raise SystemExit(main())
