"""Serving micro-batcher: coalesced device dispatch correctness.

The batched path must be indistinguishable from per-request topk_dot calls
(the reference's per-request partition fan-out, ALSServingModel.java:
264-279), under concurrency, mixed k, and mid-window model swaps.
"""

import threading

import numpy as np
import jax.numpy as jnp
import pytest

from oryx_tpu.ops.als import topk_dot
from oryx_tpu.serving.batcher import TopKBatcher, k_bucket, _Pending
from concurrent.futures import Future


@pytest.fixture
def y():
    rng = np.random.default_rng(3)
    return jnp.asarray(rng.normal(size=(200, 8)), dtype=jnp.float32)


def _direct(vec, k, y):
    vals, idx = topk_dot(jnp.asarray(vec, dtype=jnp.float32), y, k=k)
    return np.asarray(vals), np.asarray(idx)


def test_k_bucket():
    assert k_bucket(1) == 16
    assert k_bucket(16) == 16
    # 17..32 stay on the fused-kernel-eligible 32 bucket (a default
    # howMany=10 overfetches to 18)
    assert k_bucket(17) == 32
    assert k_bucket(33) == 128
    assert k_bucket(128) == 128
    assert k_bucket(129) == 1024
    assert k_bucket(5000) == 8192


def test_single_submit_matches_direct(y):
    b = TopKBatcher()
    vec = np.random.default_rng(0).normal(size=8).astype(np.float32)
    vals, idx = b.submit(vec, 10, y)
    dvals, didx = _direct(vec, 10, y)
    assert list(idx) == list(didx)
    np.testing.assert_allclose(vals, dvals, rtol=1e-5)
    b.close()


def test_concurrent_submits_all_correct(y):
    b = TopKBatcher()
    rng = np.random.default_rng(1)
    vecs = rng.normal(size=(32, 8)).astype(np.float32)
    results = [None] * 32
    ks = [5 + (i % 7) for i in range(32)]

    def go(i):
        results[i] = b.submit(vecs[i], ks[i], y)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(32):
        vals, idx = results[i]
        assert len(idx) == ks[i]
        dvals, didx = _direct(vecs[i], ks[i], y)
        assert list(idx) == list(didx)
        np.testing.assert_allclose(vals, dvals, rtol=1e-5)
    b.close()


def test_dispatch_groups_by_matrix_and_bucket(y):
    """One window containing two target matrices and two k buckets must
    produce correct per-request results (a MODEL swap mid-window splits the
    dispatch, it doesn't corrupt it)."""
    rng = np.random.default_rng(2)
    y2 = jnp.asarray(rng.normal(size=(50, 8)), dtype=jnp.float32)
    b = TopKBatcher()
    reqs = []
    for i in range(6):
        tgt = y if i % 2 == 0 else y2
        k = 3 if i < 3 else 20
        vec = rng.normal(size=8).astype(np.float32)
        reqs.append(_Pending(vec, k, tgt, Future()))
    for item in b._launch(reqs):
        b._resolve(item)
    assert b.dispatches == 4  # 2 matrices x 2 k-buckets
    assert b.coalesced == 6
    for p in reqs:
        vals, idx = p.future.result(timeout=5)
        k_eff = min(p.k, p.y.shape[0])
        assert len(idx) == k_eff
        dvals, didx = _direct(p.vec, k_eff, p.y)
        assert list(idx) == list(didx)
        np.testing.assert_allclose(vals, dvals, rtol=1e-5)


def test_k_larger_than_items():
    rng = np.random.default_rng(4)
    small = jnp.asarray(rng.normal(size=(7, 4)), dtype=jnp.float32)
    b = TopKBatcher()
    vals, idx = b.submit(rng.normal(size=4).astype(np.float32), 50, small)
    assert len(idx) == 7  # capped at item count
    b.close()


def test_shared_is_singleton():
    assert TopKBatcher.shared() is TopKBatcher.shared()

# ---------------------------------------------------------------------------
# wedged-device failover (round-2 lesson: the tunneled TPU can hang an
# in-flight transfer forever; the serving tier must degrade, not die)
# ---------------------------------------------------------------------------


from oryx_tpu.ops.als import topk_dot_batch as _real_topk_dot_batch
from e2e_common import WedgeHook


def _WedgeHook():
    return WedgeHook(_real_topk_dot_batch, block_first_only=True)


def _host_mat(y):
    return np.asarray(y, dtype=np.float32)


def test_wedged_dispatch_fails_over_to_host(y, monkeypatch):
    hook = _WedgeHook()
    monkeypatch.setattr(
        "oryx_tpu.ops.als.topk_dot_batch", hook, raising=True
    )
    b = TopKBatcher(device_timeout=0.5, probe_interval=0.2, compile_timeout=0.5)
    vec = np.random.default_rng(0).normal(size=8).astype(np.float32)
    # the dispatch wedges; the watchdog must host-resolve within ~timeout
    vals, idx = b.submit(vec, 10, y, host_mat=_host_mat(y))
    assert b.device_failovers == 1
    dvals, didx = _direct(vec, 10, y)
    assert list(idx) == list(didx)
    np.testing.assert_allclose(vals, dvals, rtol=1e-5)
    # while down, new submits take the host path immediately
    vals2, idx2 = b.submit(vec, 10, y, host_mat=_host_mat(y))
    assert list(idx2) == list(didx)
    assert b.host_fallbacks >= 2
    hook.release.set()
    b.close()


def test_wedged_dispatch_without_host_mat_errors(y, monkeypatch):
    hook = _WedgeHook()
    monkeypatch.setattr(
        "oryx_tpu.ops.als.topk_dot_batch", hook, raising=True
    )
    b = TopKBatcher(device_timeout=0.5, probe_interval=0.2, compile_timeout=0.5)
    vec = np.random.default_rng(0).normal(size=8).astype(np.float32)
    with pytest.raises(RuntimeError):
        b.submit(vec, 10, y)
    hook.release.set()
    b.close()


def test_device_recovery_resumes_device_path(y, monkeypatch):
    hook = _WedgeHook()
    monkeypatch.setattr(
        "oryx_tpu.ops.als.topk_dot_batch", hook, raising=True
    )
    b = TopKBatcher(device_timeout=0.4, probe_interval=0.1, compile_timeout=0.4)
    vec = np.random.default_rng(0).normal(size=8).astype(np.float32)
    b.submit(vec, 10, y, host_mat=_host_mat(y))  # wedge + failover
    assert b._device_down.is_set()
    hook.release.set()  # transport recovers
    # submits keep working throughout; eventually a probe flips the path
    deadline = __import__("time").time() + 10
    while b._device_down.is_set() and __import__("time").time() < deadline:
        b.submit(vec, 10, y, host_mat=_host_mat(y))
        __import__("time").sleep(0.05)
    assert not b._device_down.is_set(), "probe never recovered the device"
    # device path again: a fresh dispatcher thread serves the queue
    vals, idx = b.submit(vec, 10, y, host_mat=_host_mat(y))
    dvals, didx = _direct(vec, 10, y)
    assert list(idx) == list(didx)
    b.close()


def test_first_dispatch_compile_grace_defers_watchdog(y, monkeypatch):
    """A first dispatch of a shape that runs past device_timeout but within
    compile_timeout is a cold XLA compile, not a wedge: the watchdog must
    not fail it over to host scoring (round-4 window post-mortem — a
    remote-compile tunnel takes tens of seconds per cold shape, and a
    misread here permanently degrades the device path)."""
    import threading
    import time as _time

    hook = _WedgeHook()
    monkeypatch.setattr("oryx_tpu.ops.als.topk_dot_batch", hook, raising=True)
    b = TopKBatcher(device_timeout=0.3, probe_interval=0.1, compile_timeout=15.0)
    vec = np.random.default_rng(0).normal(size=8).astype(np.float32)
    threading.Thread(
        target=lambda: (_time.sleep(1.2), hook.release.set()), daemon=True
    ).start()
    vals, idx = b.submit(vec, 10, y, host_mat=_host_mat(y))
    assert b.device_failovers == 0
    assert b.host_fallbacks == 0
    dvals, didx = _direct(vec, 10, y)
    assert list(idx) == list(didx)
    np.testing.assert_allclose(vals, dvals, rtol=1e-5)
    b.close()


def test_accel_batch_padding_two_buckets():
    """On an accelerator the batch dimension pads to only two buckets (the
    scan is bandwidth-bound in Y, and each extra shape is a cold compile
    over the tunnel); on CPU it stays fine-grained pow2."""
    from oryx_tpu.serving.batcher import MAX_BATCH, _pad_rows

    assert _pad_rows(1, True) == 512
    assert _pad_rows(512, True) == 512
    assert _pad_rows(513, True) == MAX_BATCH
    # beyond the ladder (custom max_batch): unpadded, never shrunk
    assert _pad_rows(MAX_BATCH + 1, True) == MAX_BATCH + 1
    assert _pad_rows(1, False) == 1
    assert _pad_rows(22, False) == 32
    assert _pad_rows(513, False) == 1024


def test_host_topk_cosine_matches_numpy(y):
    from oryx_tpu.serving.batcher import host_topk

    hm = _host_mat(y)
    vec = np.random.default_rng(5).normal(size=8).astype(np.float32)
    vals, idx = host_topk(vec, 5, hm, cosine=True)
    ref = (hm @ vec) / np.maximum(np.linalg.norm(hm, axis=1), 1e-12)
    order = np.argsort(-ref)[:5]
    assert list(idx) == list(order)
    np.testing.assert_allclose(vals, ref[order], rtol=1e-5)


def test_recall_groups_and_approx_path(y):
    """Requests with different recall targets dispatch in separate groups,
    and the approx path (exact on CPU) returns correct top-k."""
    b = TopKBatcher()
    vec = np.random.default_rng(6).normal(size=8).astype(np.float32)
    reqs = [
        _Pending(vec, 5, y, Future(), recall=1.0),
        _Pending(vec, 5, y, Future(), recall=0.95),
    ]
    for item in b._launch(reqs):
        b._resolve(item)
    assert b.dispatches == 2  # split by recall
    dvals, didx = _direct(vec, 5, y)
    for p in reqs:
        vals, idx = p.future.result(timeout=5)
        assert list(idx) == list(didx)  # CPU approx_max_k is exact
    b.close()


def test_serving_model_approx_recall_wired():
    """oryx.als.approx-recall reaches the model and the batcher dispatch."""
    from oryx_tpu.apps.als.serving import ALSServingModel, ALSServingModelManager
    from oryx_tpu.apps.als.state import ALSState
    from oryx_tpu.common.config import load_config

    import json

    rng = np.random.default_rng(1)
    cfg = load_config(overlay={"oryx.als.approx-recall": 0.9})
    mgr = ALSServingModelManager(cfg)
    # MODEL header then UP rows, as the update topic would deliver them:
    # the MANAGER must construct its model with the configured recall
    mgr.consume_key_message(
        "MODEL",
        json.dumps({"app": "als", "extensions": {"features": "4"}, "content": {}}),
    )
    mgr.consume_key_message("UP", json.dumps(["Y", "i0", [0.1, 0.2, 0.3, 0.4]]))
    mgr.consume_key_message("UP", json.dumps(["Y", "i1", [0.4, 0.3, 0.2, 0.1]]))
    mgr.consume_key_message("UP", json.dumps(["X", "u0", [1, 0, 0, 0]]))
    assert mgr.model is not None
    assert mgr.model.approx_recall == 0.9
    out = mgr.model.top_n(np.ones(4, dtype=np.float32), 2)
    assert len(out) == 2
    # bad config fails when the app config view is built, not at serve time
    from oryx_tpu.apps.als.common import ALSConfig

    with pytest.raises(ValueError, match="approx-recall"):
        ALSConfig.from_config(load_config(overlay={"oryx.als.approx-recall": 0.0}))
