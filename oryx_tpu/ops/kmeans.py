"""k-means — pjit-sharded Lloyd's iterations + k-means|| init + metrics.

TPU-native re-design of the reference's k-means compute path (app/
oryx-app-mllib .../kmeans/KMeansUpdate.java:104-116 invoking MLlib
KMeans.train, with k-means|| or random init):

- Each Lloyd iteration is two MXU ops over the whole dataset: a [N,K]
  distance matrix via the ||x||^2 - 2x.c + ||c||^2 expansion (the x.c term
  is one [N,D]x[D,K] matmul), then centroid recomputation as a one-hot
  [K,N]x[N,D] matmul — segment-sum expressed as matrix product so XLA maps
  it onto the systolic array. Points shard over the mesh "data" axis;
  XLA inserts the psum for the per-shard partial center sums.

- k-means|| init (Bahmani et al.) oversamples ~2k candidates per round by
  distance-proportional sampling, then reduces the weighted candidate set
  to k centers with weighted Lloyd — matching MLlib's K_MEANS_PARALLEL
  default; "random" picks k distinct points.

- Metrics mirror app/oryx-app-mllib .../kmeans/{SumSquaredError,
  DaviesBouldinIndex,DunnIndex,SilhouetteCoefficient}.java semantics:
  euclidean distances, mean-distance cluster scatter, silhouette over a
  bounded sample with single-point clusters contributing 0.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.common.rng import RandomManager

SILHOUETTE_MAX_SAMPLE = 4096


# ---------------------------------------------------------------------------
# assignment + training
# ---------------------------------------------------------------------------

@jax.jit
def _sq_dists(points, centers):
    """[N,K] squared euclidean distances via the matmul expansion."""
    p2 = jnp.sum(points * points, axis=1, keepdims=True)
    c2 = jnp.sum(centers * centers, axis=1)
    cross = points @ centers.T
    return jnp.maximum(p2 - 2.0 * cross + c2[None, :], 0.0)


@jax.jit
def assign_clusters(points, centers):
    """-> (cluster ids [N] int32, distance-to-nearest [N] f32)."""
    d2 = _sq_dists(points, centers)
    ids = jnp.argmin(d2, axis=1).astype(jnp.int32)
    return ids, jnp.sqrt(jnp.min(d2, axis=1))


@partial(jax.jit, static_argnames=("iterations",))
def lloyd_jit(points, weights, centers0, *, iterations: int):
    """Weighted Lloyd's as one compiled lax.scan. Zero-weight rows (padding)
    can never move a centroid; empty clusters keep their previous center."""

    def body(centers, _):
        d2 = _sq_dists(points, centers)
        ids = jnp.argmin(d2, axis=1)
        onehot = (
            jax.nn.one_hot(ids, centers.shape[0], dtype=jnp.float32)
            * weights[:, None]
        )
        sums = onehot.T @ points  # [K,D] segment-sum as matmul
        counts = onehot.sum(axis=0)
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers
        )
        return new_centers, None

    centers, _ = jax.lax.scan(body, centers0, None, length=iterations)
    # final assignment for cluster sizes
    ids = jnp.argmin(_sq_dists(points, centers), axis=1)
    counts = (
        jax.nn.one_hot(ids, centers.shape[0], dtype=jnp.float32) * weights[:, None]
    ).sum(axis=0)
    return centers, counts


def _pad_centers_pow2(centers: np.ndarray) -> np.ndarray:
    """Pad the center count to a power of two by REPEATING row 0: argmin
    returns the first of tied rows, so a padding duplicate can never win
    over the original and assignments/distances are unchanged. (Infinity
    padding would poison the expanded ||p||^2 - 2p.c + ||c||^2 distance
    form.) The jit cache then sees a handful of shapes instead of one per
    candidate-set size — the growing k-means|| candidate set was
    recompiling the distance kernel, and re-uploading the full point set,
    every round."""
    c = len(centers)
    p = 1 << max(0, c - 1).bit_length()
    if p == c:
        return centers
    pad = np.broadcast_to(centers[0], (p - c, centers.shape[1]))
    return np.concatenate([centers, pad])


def _kmeans_parallel_init(
    points: np.ndarray, weights: np.ndarray, k: int, key, rounds: int = 5
) -> np.ndarray:
    """k-means|| oversampling, reduced to k centers by weighted Lloyd."""
    n = len(points)
    keys = jax.random.split(key, rounds + 2)
    first = int(jax.random.randint(keys[0], (), 0, n))
    candidates = [points[first]]
    new = [points[first]]
    ell = 2 * k
    from oryx_tpu.ops.transfer import staged_device_put

    pts_j = staged_device_put(points)  # chunked host->device upload, reused all rounds
    d2 = None  # running min squared distance to ANY candidate so far:
    # each round only scores the centers added last round, instead of
    # rescanning the whole growing candidate set (2-3x less distance work)
    for r in range(rounds):
        if new:  # an empty round keeps d2 and simply redraws below
            _, dist = assign_clusters(
                pts_j, jnp.asarray(_pad_centers_pow2(np.stack(new)))
            )
            nd2 = np.asarray(dist, dtype=np.float64) ** 2
            d2 = nd2 if d2 is None else np.minimum(d2, nd2)
        dw = d2 * weights
        total = dw.sum()
        if total <= 0:
            break
        prob = np.minimum(1.0, ell * dw / total)
        draw = np.asarray(
            jax.random.uniform(keys[r + 1], (n,), dtype=jnp.float32)
        )
        picked = np.nonzero(draw < prob)[0]
        new = [points[j] for j in picked]
        candidates.extend(new)
        if len(candidates) >= max(ell * rounds, k):
            break
    cand = np.unique(np.stack(candidates), axis=0)
    if len(cand) <= k:
        # not enough distinct candidates: fill with random distinct points
        # (own key — reusing keys[-1] would correlate with the k-subset draw)
        fill_key, _ = jax.random.split(keys[-1])
        extra_idx = np.asarray(
            jax.random.choice(fill_key, n, (min(n, 2 * k),), replace=False)
        )
        cand = np.unique(np.concatenate([cand, points[extra_idx]]), axis=0)
    # duplicate-heavy data may simply not have k distinct points: clamp,
    # matching the reference's tolerance of k > distinct-count inputs
    k = min(k, len(cand))
    # weight candidates by the total point weight attracted to each.
    # (padding rows duplicate row 0 and argmin keeps the FIRST of tied
    # rows, so ids stay within len(cand) — see _pad_centers_pow2)
    ids, _ = assign_clusters(pts_j, jnp.asarray(_pad_centers_pow2(cand)))
    w = np.zeros(len(cand), dtype=np.float32)
    np.add.at(w, np.asarray(ids), weights.astype(np.float32))
    # reduce candidates -> k centers: weighted k-means++ seeding over the
    # candidate set, then weighted Lloyd refinement (Bahmani et al.'s
    # prescribed recluster step). Seeding from a RANDOM k-subset instead
    # loses well-separated clusters outright — Lloyd over the candidates
    # cannot move a center across the empty space between far blobs, so a
    # blob the subset missed stays missed (caught by the k-means quality
    # gate: 5 of 12 planted blobs lost, SSE 4.2x the generating centers)
    seeds = _weighted_kmeanspp(cand, w, k, keys[-1])
    centers, _ = lloyd_jit(
        jnp.asarray(cand), jnp.asarray(w), jnp.asarray(seeds), iterations=10
    )
    return np.asarray(centers)


def _weighted_kmeanspp(
    cand: np.ndarray, w: np.ndarray, k: int, key
) -> np.ndarray:
    """Weighted MAXIMIN (farthest-point) seeding over the candidate set —
    the k-means|| reduction's seeding step. The heaviest candidate seeds
    first; each next seed is argmax over d^2-to-nearest-seed times
    attracted weight. Deterministic coverage is the point: sampling
    proportional to d^2*w (classic k-means++) still skips a
    well-separated cluster with ~P(within-blob mass / total) at every
    step — measured 2-4 of 12 planted blobs lost — while argmax cannot,
    because an uncovered cluster's candidates dominate d^2*w outright.
    Outlier sensitivity (maximin's usual weakness) is damped by w: a
    stray candidate attracts almost no point mass. The randomness of
    k-means|| lives in the oversampling rounds that BUILT the candidate
    set (key kept for signature stability; unused).
    """
    del key
    wf = np.asarray(w, dtype=np.float64)
    first = int(wf.argmax())
    chosen = [first]
    d2 = ((cand - cand[first]) ** 2).sum(axis=1).astype(np.float64)
    for _ in range(1, k):
        pw = d2 * wf
        if pw.max() <= 0:
            # all remaining candidates coincide with chosen seeds:
            # duplicates are harmless (Lloyd merges them; k was already
            # clamped to the distinct-candidate count)
            chosen.append(first)
            continue
        idx = int(pw.argmax())
        chosen.append(idx)
        d2 = np.minimum(d2, ((cand - cand[idx]) ** 2).sum(axis=1))
    return cand[np.asarray(chosen, dtype=np.int64)]


@dataclass
class KMeansModelArrays:
    centers: np.ndarray  # [K,D] f32
    counts: np.ndarray  # [K] int64 cluster sizes on training data


def train_kmeans(
    points: np.ndarray,
    k: int,
    iterations: int = 30,
    init: str = "k-means||",
    mesh=None,
    seed_key=None,
    runs: int = 1,
) -> KMeansModelArrays:
    """Train k-means. With a mesh, points shard over the "data" axis and the
    whole scan runs SPMD (centers replicated, partial sums psum'd).

    runs > 1 restarts from fresh inits and keeps the lowest-SSE result
    (the oryx.kmeans.runs knob; guards random init's local optima)."""
    points = np.asarray(points, dtype=np.float32)
    points = points[~np.isnan(points).any(axis=1)]
    n = len(points)
    if n == 0:
        raise ValueError("no valid points")
    if runs > 1:
        key = seed_key if seed_key is not None else RandomManager.get_key()
        best, best_sse = None, np.inf
        for rk in jax.random.split(key, runs):
            m = train_kmeans(points, k, iterations, init, mesh, seed_key=rk)
            sse = sum_squared_error(points, m.centers)
            if best is None or sse < best_sse:
                best, best_sse = m, sse
        return best
    if k >= n:
        # only in this degenerate regime is the distinct-row count worth
        # computing; a full-dataset np.unique on every call would dominate
        # host time for large N
        k = min(k, len(np.unique(points, axis=0)))
    key = seed_key if seed_key is not None else RandomManager.get_key()
    k_init, k_run = jax.random.split(key)

    weights = np.ones(n, dtype=np.float32)
    if init == "random":
        # sample k *distinct points* (not merely distinct indices):
        # resample over progressively larger candidate draws, falling back
        # to a full distinct scan only if duplicates persist
        centers0 = None
        for attempt in range(3):
            k_init, sub = jax.random.split(k_init)
            draw = np.asarray(
                jax.random.choice(sub, n, (min(n, k * (2**attempt)),), replace=False)
            )
            uniq = np.unique(points[draw], axis=0)  # note: sorts rows
            if len(uniq) >= k:
                break
        else:
            uniq = np.unique(points, axis=0)
        k = min(k, len(uniq))
        k_init, sub = jax.random.split(k_init)
        pick = np.asarray(jax.random.choice(sub, len(uniq), (k,), replace=False))
        centers0 = uniq[pick]
    else:
        centers0 = _kmeans_parallel_init(points, weights, k, k_init)

    p, w = points, weights
    if mesh is not None:
        from oryx_tpu.parallel.mesh import DATA_AXIS, shard_array

        axis = mesh.shape[DATA_AXIS]
        pad = (-n) % axis
        if pad:
            # zero-weight padding rows: never move a centroid
            p = np.concatenate([p, np.zeros((pad, p.shape[1]), dtype=np.float32)])
            w = np.concatenate([w, np.zeros(pad, dtype=np.float32)])
        p = shard_array(p, mesh)
        w = shard_array(w, mesh)

    centers, counts = lloyd_jit(
        jnp.asarray(p), jnp.asarray(w), jnp.asarray(centers0), iterations=iterations
    )
    return KMeansModelArrays(
        np.asarray(centers), np.asarray(counts).round().astype(np.int64)
    )


# ---------------------------------------------------------------------------
# evaluation metrics (KMeansUpdate.java:137-173 strategies)
# ---------------------------------------------------------------------------

def _cluster_metrics(points: np.ndarray, centers: np.ndarray):
    """Per-cluster (count, mean dist, sum sq dist) over assigned points —
    the ClusterMetric reduction of AbstractKMeansEvaluation.java."""
    ids, dist = assign_clusters(jnp.asarray(points), jnp.asarray(centers))
    ids, dist = np.asarray(ids), np.asarray(dist, dtype=np.float64)
    k = len(centers)
    counts = np.bincount(ids, minlength=k).astype(np.float64)
    sum_d = np.bincount(ids, weights=dist, minlength=k)
    sum_d2 = np.bincount(ids, weights=dist**2, minlength=k)
    mean_d = np.divide(sum_d, counts, out=np.zeros(k), where=counts > 0)
    return ids, counts, mean_d, sum_d2


def sum_squared_error(points: np.ndarray, centers: np.ndarray) -> float:
    _, _, _, sum_d2 = _cluster_metrics(points, centers)
    return float(sum_d2.sum())


def davies_bouldin_index(points: np.ndarray, centers: np.ndarray) -> float:
    """Lower is better; for each cluster i, max over j of
    (scatter_i + scatter_j) / d(center_i, center_j), averaged."""
    _, _, mean_d, _ = _cluster_metrics(points, centers)
    k = len(centers)
    if k < 2:
        return 0.0
    cd = np.sqrt(
        np.maximum(np.asarray(_sq_dists(jnp.asarray(centers), jnp.asarray(centers))), 0)
    )
    total = 0.0
    for i in range(k):
        ratios = [
            (mean_d[i] + mean_d[j]) / cd[i, j]
            for j in range(k)
            if j != i and cd[i, j] > 0
        ]
        total += max(ratios) if ratios else 0.0
    return total / k


def dunn_index(points: np.ndarray, centers: np.ndarray) -> float:
    """Higher is better: min inter-centroid distance over max mean
    intra-cluster distance."""
    _, _, mean_d, _ = _cluster_metrics(points, centers)
    k = len(centers)
    if k < 2:
        return 0.0
    cd = np.sqrt(
        np.maximum(np.asarray(_sq_dists(jnp.asarray(centers), jnp.asarray(centers))), 0)
    )
    inter = min(cd[i, j] for i in range(k) for j in range(i + 1, k))
    intra = mean_d.max()
    return float(inter / intra) if intra > 0 else 0.0


@jax.jit
def _silhouette_jit(points, centers):
    """Vectorized silhouette: one [S,S] pairwise-distance matmul and a
    [S,K] per-cluster mean-distance reduction; singleton clusters
    contribute 0 (SilhouetteCoefficient.java's convention)."""
    d = jnp.sqrt(_sq_dists(points, points))  # [S,S]
    ids, _ = assign_clusters(points, centers)
    k = centers.shape[0]
    onehot = jax.nn.one_hot(ids, k, dtype=jnp.float32)  # [S,K]
    n_c = onehot.sum(axis=0)  # [K]
    sum_to_cluster = d @ onehot  # [S,K]
    own_n = n_c[ids]
    a = jnp.take_along_axis(sum_to_cluster, ids[:, None], axis=1)[:, 0] / jnp.maximum(
        own_n - 1.0, 1.0
    )
    mean_other = jnp.where(
        (n_c[None, :] > 0) & (jax.nn.one_hot(ids, k) == 0),
        sum_to_cluster / jnp.maximum(n_c[None, :], 1.0),
        jnp.inf,
    )
    b = jnp.min(mean_other, axis=1)
    m = jnp.maximum(a, b)
    s = jnp.where((own_n > 1) & (m > 0) & jnp.isfinite(b), (b - a) / m, 0.0)
    return s.mean()


def silhouette_coefficient(
    points: np.ndarray, centers: np.ndarray, seed_key=None
) -> float:
    """Mean silhouette over a bounded sample (the [S,S] distance matrix
    caps S; the reference also evaluates on a sample)."""
    points = np.asarray(points, dtype=np.float32)
    if len(points) > SILHOUETTE_MAX_SAMPLE:
        key = seed_key if seed_key is not None else RandomManager.get_key()
        idx = np.asarray(
            jax.random.choice(key, len(points), (SILHOUETTE_MAX_SAMPLE,), replace=False)
        )
        points = points[idx]
    return float(_silhouette_jit(jnp.asarray(points), jnp.asarray(centers)))


def online_update(
    center: np.ndarray, count: int, new_point: np.ndarray, new_count: int
) -> tuple[np.ndarray, int]:
    """ClusterInfo.update (app/oryx-app-common .../kmeans/ClusterInfo.java:52):
    shift the centroid toward the new (mean) point by newCount/total."""
    center = np.asarray(center, dtype=np.float64)
    total = count + new_count
    frac = new_count / total
    return center + frac * (np.asarray(new_point, dtype=np.float64) - center), total
