#!/bin/bash
# Poll TPU health in killable subprocesses; append timestamped lines to
# .tpu_health.log. On the FIRST healthy probe, automatically fire one full
# bench run (lockfile-guarded) so a healthy window is never wasted waiting
# for a human: artifacts land in .tpu_window_bench.{out,err}.
case "${1:-}" in
  --*) echo "usage: tpu_poll.sh [logfile] [interval_s] (no flags)" >&2; exit 2;;
esac
LOG="${1:-/root/repo/.tpu_health.log}"
INTERVAL="${2:-240}"
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOCK="$REPO/.tpu_window_bench.lock"
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 45 python -c 'import jax,jax.numpy as jnp; x=jnp.ones((512,512),jnp.bfloat16); (x@x).block_until_ready(); d=jax.devices()[0]; print(d.platform)' 2>&1)
  rc=$?
  if [ $rc -eq 0 ]; then
    echo "$ts HEALTHY $(echo "$out" | tail -1)" >> "$LOG"
    if mkdir "$LOCK" 2>/dev/null; then
      echo "$ts HEALTHY -> launching window bench" >> "$LOG"
      (cd "$REPO" && ORYX_BENCH_BUDGET_S=3000 timeout 3300 python bench.py \
        > "$REPO/.tpu_window_bench.out" 2> "$REPO/.tpu_window_bench.err"; \
       echo "$(date -u +%FT%TZ) window bench rc=$?" >> "$LOG"; \
       python "$REPO/tools/bank_window.py" "${ORYX_ROUND:-auto}" \
         >> "$LOG" 2>&1) &
    fi
  else
    echo "$ts WEDGED rc=$rc" >> "$LOG"
  fi
  sleep "$INTERVAL"
done
