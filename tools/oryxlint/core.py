"""oryxlint core: project model, annotations, suppression, checker SPI.

A ``Project`` holds every source module in scope parsed once (AST +
raw lines + per-line annotations); checkers receive the whole project so
cross-module reasoning (call graphs, class indexes) is cheap and shared.

Annotation grammar (trailing comments, parsed per line):

- suppression: ``oryxlint: disable=<rule>[,<rule>...]`` — suppresses
  findings of those rules reported on the same line (or the line
  directly below, for call sites wrapped past the comment). Naming a
  rule id that no checker defines is itself a finding (rule
  ``unknown-rule``), so a typo cannot silently disable nothing.
- off-loop proof: ``oryxlint: offloop`` on a ``def`` line — the function
  is proven to run on a worker thread, never an event loop; the
  blocking-call walk does not traverse into it.
- lock contract: ``oryxlint: holds=<lockattr>[,<lockattr>...]`` on a
  ``def`` line — every caller holds those locks (the machine-checked
  form of a "call under _lock" docstring); guarded-attribute accesses
  inside the function are treated as locked.
- guarded attribute: ``guarded-by: <lockattr>[|<alt>...]`` trailing an
  attribute assignment (normally its ``__init__`` declaration). Accesses
  of that attribute elsewhere in the class must hold one of the named
  locks. A ``(writes)`` qualifier restricts the check to stores — the
  idiom for snapshot-swap state whose reads are deliberately lock-free.
- donation contract: ``oryxlint: donates=<pos>`` on a ``def`` line
  declares a hand-written wrapper whose positional argument ``pos`` is
  consumed like a ``donate_argnums`` buffer; ``donates=<pos> when
  <kwarg>`` restricts it to call sites passing that keyword as a
  literal ``True`` (the conditional-donation wrapper idiom).
- terminal read: ``oryxlint: sink`` on a use (or read) line — the
  dataflow ``param-dropped`` rule treats the annotated use as an
  intentional terminal consumption of the value, even though it is
  neither a call argument, an attribute store, nor a returned value.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

ANN_DISABLE = re.compile(r"#\s*oryxlint:\s*disable=([A-Za-z0-9_,\- ]+)")
ANN_OFFLOOP = re.compile(r"#\s*oryxlint:\s*offloop\b")
ANN_HOLDS = re.compile(r"#\s*oryxlint:\s*holds=([A-Za-z0-9_,| ]+)")
ANN_GUARDED = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z0-9_|.]+)(?:\s*\((writes)\))?"
)
ANN_DONATES = re.compile(
    r"#\s*oryxlint:\s*donates=(\d+)(?:\s+when\s+([A-Za-z_][A-Za-z0-9_]*))?"
)
ANN_SINK = re.compile(r"#\s*oryxlint:\s*sink\b")


@dataclass
class Finding:
    """One rule violation at a source location.

    ``severity`` and ``fix_hint`` are rule-level metadata attached by
    ``run_lint`` from the checker catalogs — stable fields of the
    ``--json`` schema (consumed by tools/precommit.sh for grouped
    display). The tier-1 gate fails on any active finding regardless of
    severity; the field is display/triage metadata, not policy."""

    path: str  # repo-relative
    line: int
    rule: str
    message: str
    severity: str = "error"
    fix_hint: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "severity": self.severity,
            "fix_hint": self.fix_hint,
            "message": self.message,
        }


class SourceModule:
    """One parsed source file plus its per-line oryxlint annotations."""

    def __init__(self, path: Path, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=relpath)
        # line -> set of rule ids disabled there
        self.disables: dict[int, set[str]] = {}
        # def lines annotated offloop / holds=<locks>
        self.offloop_lines: set[int] = set()
        self.holds_lines: dict[int, tuple[str, ...]] = {}
        # line -> (lock alternatives, writes_only) for guarded-by comments
        self.guarded_lines: dict[int, tuple[tuple[str, ...], bool]] = {}
        # def lines annotated donates=<pos> [when <kwarg>]
        self.donates_lines: dict[int, tuple[int, str | None]] = {}
        # lines annotated `oryxlint: sink` (intentional terminal reads)
        self.sink_lines: set[int] = set()
        for i, ln in enumerate(self.lines, start=1):
            if "#" not in ln:
                continue
            m = ANN_DISABLE.search(ln)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.disables.setdefault(i, set()).update(rules)
            if ANN_OFFLOOP.search(ln):
                self.offloop_lines.add(i)
            m = ANN_HOLDS.search(ln)
            if m:
                locks = tuple(
                    t.strip() for t in re.split(r"[|,]", m.group(1)) if t.strip()
                )
                self.holds_lines[i] = locks
            m = ANN_GUARDED.search(ln)
            if m:
                alts = tuple(
                    t.strip() for t in m.group(1).split("|") if t.strip()
                )
                self.guarded_lines[i] = (alts, m.group(2) == "writes")
            m = ANN_DONATES.search(ln)
            if m:
                self.donates_lines[i] = (int(m.group(1)), m.group(2))
            if ANN_SINK.search(ln):
                self.sink_lines.add(i)

    def decorated_span(self, node) -> range:
        """Line range covering a def and its decorators (annotations on
        either count for the function)."""
        start = min(
            [node.lineno] + [d.lineno for d in getattr(node, "decorator_list", [])]
        )
        return range(start, node.body[0].lineno if node.body else node.lineno + 1)

    def fn_offloop(self, node) -> bool:
        return any(i in self.offloop_lines for i in self.decorated_span(node))

    def fn_holds(self, node) -> tuple[str, ...]:
        out: tuple[str, ...] = ()
        for i in self.decorated_span(node):
            out += self.holds_lines.get(i, ())
        return out

    def fn_donates(self, node) -> tuple[int, str | None] | None:
        for i in self.decorated_span(node):
            if i in self.donates_lines:
                return self.donates_lines[i]
        return None


# Default lint scope relative to the repo root. tests/ hosts deliberate
# violation fixtures; tools/oryxlint/ hosts the annotation grammar itself
# (its docstrings would self-trigger the comment scanners).
SCOPE_DIRS = ("oryx_tpu",)
SCOPE_TOP_FILES = ("bench.py",)
SCOPE_TOOL_GLOB = "tools/*.py"


class Project:
    """Every source module in lint scope, parsed once."""

    def __init__(self, root: Path, modules: list[SourceModule]):
        self.root = Path(root)
        self.modules = modules

    @classmethod
    def load(cls, root: str | Path, files: list[str] | None = None) -> "Project":
        root = Path(root).resolve()
        paths: list[Path] = []
        if files is None:
            for d in SCOPE_DIRS:
                paths.extend(sorted((root / d).rglob("*.py")))
            for f in SCOPE_TOP_FILES:
                if (root / f).exists():
                    paths.append(root / f)
            paths.extend(sorted(root.glob(SCOPE_TOOL_GLOB)))
        else:
            paths = [root / f for f in files]
        modules: list[SourceModule] = []
        for p in paths:
            if "__pycache__" in p.parts or not p.exists():
                continue
            rel = str(p.relative_to(root))
            text = p.read_text(encoding="utf-8")
            modules.append(SourceModule(p, rel, text))
        return cls(root, modules)

    def module(self, relpath: str) -> SourceModule | None:
        for m in self.modules:
            if m.relpath == relpath:
                return m
        return None


class Checker:
    """Checker SPI: subclasses declare their rule catalog and visit the
    project. ``rules`` maps rule id -> one-line description (surfaced by
    ``--list-rules`` and validated against suppression comments).
    ``severities`` (rule id -> "error"|"warning", default "error") and
    ``fix_hints`` (rule id -> one-line remediation) feed the stable
    per-finding ``severity``/``fix_hint`` fields of the --json schema."""

    name = "checker"
    rules: dict[str, str] = {}
    severities: dict[str, str] = {}
    fix_hints: dict[str, str] = {}

    def check(self, project: Project) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


def _all_checkers() -> list[Checker]:
    from tools.oryxlint.checkers import ALL_CHECKERS

    return [cls() for cls in ALL_CHECKERS]


def known_rules(checkers: list[Checker] | None = None) -> dict[str, str]:
    out = {"unknown-rule": "a suppression comment names a rule id no checker defines"}
    for c in checkers if checkers is not None else _all_checkers():
        out.update(c.rules)
    return out


def _unknown_rule_findings(
    project: Project, rules: dict[str, str]
) -> list[Finding]:
    out = []
    for mod in project.modules:
        for line, ids in sorted(mod.disables.items()):
            for rid in sorted(ids):
                if rid not in rules:
                    out.append(Finding(
                        mod.relpath, line, "unknown-rule",
                        f"suppression names unknown rule {rid!r} "
                        f"(known: {', '.join(sorted(rules))})",
                    ))
    return out


def _suppressed(mod: SourceModule | None, f: Finding) -> bool:
    """A finding is suppressed by a disable comment on its own line or the
    line directly above (wrapped call sites). ``unknown-rule`` findings
    are never suppressible — they flag the suppression syntax itself."""
    if f.rule == "unknown-rule" or mod is None:
        return False
    for line in (f.line, f.line - 1):
        if f.rule in mod.disables.get(line, ()):
            return True
    return False


def run_lint(
    root: str | Path,
    checkers: list[Checker] | None = None,
    changed: set[str] | None = None,
) -> tuple[list[Finding], list[Finding]]:
    """Run checkers over the tree; returns (active, suppressed) findings.

    ``changed`` (repo-relative paths) filters per-file findings to those
    files — the ``--changed`` pre-commit mode. Whole-tree consistency
    findings (reference.conf / docs / ratchet drift) always report: they
    are cheap and a stale doc row is actionable no matter which file the
    commit touches.
    """
    project = Project.load(root)
    cs = checkers if checkers is not None else _all_checkers()
    rules = known_rules(cs)
    severities = {"unknown-rule": "error"}
    fix_hints = {
        "unknown-rule": "fix the rule id in the disable comment "
        "(see --list-rules)",
    }
    for c in cs:
        severities.update(c.severities)
        fix_hints.update(c.fix_hints)
    raw: list[Finding] = []
    for c in cs:
        raw.extend(c.check(project))
    raw.extend(_unknown_rule_findings(project, rules))
    for f in raw:
        # rule-catalog metadata fills defaults only: a checker that set a
        # per-finding severity/fix_hint keeps it
        if f.severity == "error":
            f.severity = severities.get(f.rule, "error")
        if not f.fix_hint:
            f.fix_hint = fix_hints.get(f.rule, "")
    mods = {m.relpath: m for m in project.modules}
    active: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        if _suppressed(mods.get(f.path), f):
            suppressed.append(f)
        elif changed is not None and f.path in mods and f.path not in changed:
            continue  # per-file finding outside the changed set
        else:
            active.append(f)
    active.sort(key=lambda f: (f.path, f.line, f.rule))
    return active, suppressed
