"""Fleet observability plane (ISSUE 14): cross-process trace stitching,
metrics federation with exemplar fidelity, and the /fleet/status NaN
regression — the in-process/unit halves plus one real two-replica e2e
(the acceptance criterion: ONE stitched trace holding front-hop and
replica-side spans under the same trace id, and a federated OpenMetrics
page that strict parsers round-trip)."""

from __future__ import annotations

import http.client
import json
import math
import os
import subprocess
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from oryx_tpu.common.config import load_config
from oryx_tpu.common.tracing import (
    flatten_forest,
    get_tracer,
    stitch_traces,
    stitched_chrome,
)
from oryx_tpu.fleet import FleetFront
from oryx_tpu.fleet.observe import federate, inject_label, parse_exposition


# ---- federation text merging (units) ---------------------------------------


def test_inject_label_shapes():
    assert inject_label("m 1", "replica", "r0") == 'm{replica="r0"} 1'
    assert (
        inject_label('m{a="b"} 1', "replica", "r0")
        == 'm{replica="r0",a="b"} 1'
    )
    assert inject_label("m{} 1", "replica", "r0") == 'm{replica="r0"} 1'
    # a sample already carrying the label keeps its own
    assert (
        inject_label('m{replica="own"} 1', "replica", "r0")
        == 'm{replica="own"} 1'
    )
    # exemplar braces after the value are NOT the labelset
    line = 'm_bucket{le="0.1"} 3 # {trace_id="ff"} 0.05 1.5'
    assert inject_label(line, "replica", "r1") == (
        'm_bucket{replica="r1",le="0.1"} 3 # {trace_id="ff"} 0.05 1.5'
    )
    # a label name merely ENDING in "replica" is not the replica label —
    # a substring match here would collide two replicas' series into one
    assert inject_label('m{shard_replica="1"} 3', "replica", "r0") == (
        'm{replica="r0",shard_replica="1"} 3'
    )


def test_federate_dedupes_family_metadata():
    page = "# HELP m help text\n# TYPE m gauge\nm 1\n"
    merged = federate([("r0", page), ("r1", page)])
    assert merged.count("# TYPE m gauge") == 1
    assert merged.count("# HELP m help text") == 1
    assert 'm{replica="r0"} 1' in merged
    assert 'm{replica="r1"} 1' in merged


def test_federate_union_keeps_one_sided_families():
    merged = federate([
        ("r0", "# TYPE only_r0 counter\nonly_r0_total 1\n"),
        ("r1", "# TYPE only_r1 gauge\nonly_r1 2\n"),
    ])
    assert 'only_r0_total{replica="r0"} 1' in merged
    assert 'only_r1{replica="r1"} 2' in merged


def test_parse_exposition_stops_at_eof():
    fams, order = parse_exposition("# TYPE m gauge\nm 1\n# EOF\nnoise 2\n")
    assert order == ["m"] and "noise" not in fams


def test_federated_openmetrics_round_trips_strict_parser():
    """ISSUE 14 satellite: the merged page must survive
    prometheus_client's strict OpenMetrics parser with exemplars intact
    and the replica label on every series."""
    parser = pytest.importorskip("prometheus_client.openmetrics.parser")
    from oryx_tpu.common.metrics import MetricsRegistry

    pages = []
    for rid, trace in (("r0", "aa" * 16), ("r1", "bb" * 16)):
        reg = MetricsRegistry()
        reg.counter(
            "oryx_serving_requests_total", "reqs", labeled=True
        ).inc(method="GET", status="200")
        reg.histogram("oryx_serving_request_seconds", "lat").observe(
            0.003, trace_id=trace, method="GET"
        )
        pages.append((rid, reg.render_prometheus(openmetrics=True)))
    merged = federate(pages, openmetrics=True)
    fams = {f.name: f for f in parser.text_string_to_metric_families(merged)}
    assert set(fams) == {
        "oryx_serving_requests", "oryx_serving_request_seconds",
    }
    exemplars = {
        s.labels["replica"]: s.exemplar.labels["trace_id"]
        for s in fams["oryx_serving_request_seconds"].samples
        if s.exemplar
    }
    assert exemplars == {"r0": "aa" * 16, "r1": "bb" * 16}
    for f in fams.values():
        for s in f.samples:
            assert s.labels.get("replica") in ("r0", "r1")


# ---- stitching (units) -----------------------------------------------------


def _node(name, trace, span, parent=None, start=1.0, children=()):
    return {
        "name": name, "trace_id": trace, "span_id": span,
        "parent_id": parent, "start_ms": start, "duration_ms": 2.0,
        "attrs": {}, "children": list(children),
    }


def test_stitch_groups_by_trace_and_labels_processes():
    t = "t" * 32
    front = [_node("front.route", t, "f1", start=1.0,
                   children=[_node("front.proxy", t, "f2", "f1", 1.2)])]
    replica = [_node("http.request", t, "r1", "f2", 1.3)]
    other = [_node("http.request", "u" * 32, "x1", start=9.0)]
    traces = stitch_traces([("front", front), ("r0", replica + other)])
    by_id = {x["trace_id"]: x for x in traces}
    assert by_id[t]["processes"] == ["front", "r0"]
    assert [s["name"] for s in by_id[t]["spans"]] == [
        "front.route", "front.proxy", "http.request",
    ]
    assert by_id["u" * 32]["processes"] == ["r0"]


def test_stitch_dedupes_shared_rings():
    # co-resident processes (tests) can return overlapping rings; a span
    # id must appear once in the stitched trace
    t = "t" * 32
    span = _node("http.request", t, "s1")
    traces = stitch_traces([("front", [span]), ("r0", [dict(span)])])
    assert len(traces[0]["spans"]) == 1


def test_stitched_chrome_gives_each_process_a_lane():
    t = "t" * 32
    doc = stitched_chrome([
        ("front", [_node("front.route", t, "f1")]),
        ("r0", [_node("http.request", t, "r1", "f1")]),
    ])
    names = {
        e["args"]["name"] for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert names == {"front", "r0"}
    x_pids = {e["pid"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert len(x_pids) == 2  # one lane per process


def test_flatten_forest_strips_children():
    t = "t" * 32
    flat = flatten_forest(
        [_node("a", t, "1", children=[_node("b", t, "2", "1")])]
    )
    assert {s["name"] for s in flat} == {"a", "b"}
    assert all("children" not in s for s in flat)


# ---- front endpoints against stub replicas ---------------------------------


class _StubReplica:
    """Scripted backend serving /healthz, /metrics, /debug/traces, and a
    catch-all that records the traceparent it was forwarded."""

    def __init__(self, rid: str):
        self.rid = rid
        self.seen_traceparent: dict[str, str | None] = {}
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    body = b'{"status":"up","degraded":[]}'
                elif self.path == "/metrics":
                    om = "application/openmetrics-text" in (
                        self.headers.get("accept") or ""
                    )
                    text = (
                        "# HELP oryx_stub_up help\n# TYPE oryx_stub_up gauge\n"
                        f'oryx_stub_up{{src="{stub.rid}"}} 1\n'
                    )
                    body = (text + ("# EOF\n" if om else "")).encode()
                elif self.path.startswith("/debug/traces"):
                    body = json.dumps({"traces": [
                        {"name": "http.request", "trace_id": "ab" * 16,
                         "span_id": stub.rid * 4, "parent_id": None,
                         "start_ms": 5.0, "duration_ms": 1.0, "attrs": {},
                         "children": []},
                    ]}).encode()
                else:
                    stub.seen_traceparent[self.path] = self.headers.get(
                        "traceparent"
                    )
                    body = b'{"ok":true}'
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _front_for(tmp_path, backends, **overlay):
    cfg = load_config(overlay={
        "oryx.fleet.front.probe-interval-sec": 0.2,
        "oryx.monitoring.flight.dir": str(tmp_path / "front-flight"),
        **overlay,
    })
    front = FleetFront(
        cfg,
        backends=[(s.rid, "127.0.0.1", s.port) for s in backends],
        port=0,
    )
    front.start()
    return front


def _get(port, path, headers=None):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        c.request("GET", path, headers=headers or {})
        r = c.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        c.close()


def test_fleet_status_renders_nan_gauges_as_null(tmp_path):
    """ISSUE 14 small fix: a NaN per-replica gauge (mfu on a peak-less
    host) must render null, not bare NaN — pinned with a strict
    json.loads that rejects NaN tokens."""
    a = _StubReplica("r0")
    front = _front_for(tmp_path, [a])
    try:
        front.replicas[0].mfu = float("nan")
        front.replicas[0].staleness_seconds = float("inf")
        status, _, body = _get(front.port, "/fleet/status")
        assert status == 200
        assert b"NaN" not in body and b"Infinity" not in body
        doc = json.loads(
            body.decode(),
            parse_constant=lambda s: (_ for _ in ()).throw(ValueError(s)),
        )
        assert doc["replicas"][0]["mfu"] is None
        assert doc["replicas"][0]["staleness_seconds"] is None
    finally:
        front.close()
        a.close()


def test_fleet_metrics_federates_with_replica_labels(tmp_path):
    a, b = _StubReplica("r0"), _StubReplica("r1")
    front = _front_for(tmp_path, [a, b])
    try:
        status, headers, body = _get(front.port, "/fleet/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        text = body.decode()
        assert text.count("# TYPE oryx_stub_up gauge") == 1
        assert 'oryx_stub_up{replica="r0",src="r0"} 1' in text
        assert 'oryx_stub_up{replica="r1",src="r1"} 1' in text
        # OpenMetrics negotiation passes through and terminates with EOF
        status, headers, body = _get(
            front.port, "/fleet/metrics",
            headers={"Accept": "application/openmetrics-text"},
        )
        assert headers["Content-Type"].startswith(
            "application/openmetrics-text"
        )
        assert body.decode().rstrip().endswith("# EOF")
    finally:
        front.close()
        a.close()
        b.close()


def test_fleet_metrics_skips_dead_replica_and_counts_it(tmp_path):
    a = _StubReplica("r0")
    dead = _StubReplica("r1")
    front = _front_for(
        tmp_path, [a, dead],
        **{"oryx.fleet.front.probe-interval-sec": 30},  # r1 stays "routable"
    )
    dead.close()  # port now refuses connections
    try:
        status, _, body = _get(front.port, "/fleet/metrics")
        assert status == 200
        assert 'oryx_stub_up{replica="r0",src="r0"} 1' in body.decode()
        assert front._m_fed_errors.value(endpoint="/metrics", replica="r1") >= 1
    finally:
        front.close()
        a.close()


def test_fleet_traces_excludes_ejected_replicas(tmp_path):
    a, b = _StubReplica("r0"), _StubReplica("r1")
    front = _front_for(
        tmp_path, [a, b], **{"oryx.fleet.front.eject-after": 1}
    )
    try:
        b.close()  # r1 dies; prober ejects it
        deadline = time.time() + 10
        while front.replicas[1].routable:
            assert time.time() < deadline
            time.sleep(0.05)
        status, _, body = _get(front.port, "/fleet/traces")
        doc = json.loads(body)
        assert "r0" in doc["processes"] and "r1" not in doc["processes"]
    finally:
        front.close()
        a.close()


def test_front_originates_and_injects_traceparent(tmp_path):
    a = _StubReplica("r0")
    front = _front_for(
        tmp_path, [a], **{"oryx.monitoring.tracing.enabled": True}
    )
    try:
        # client sent NO traceparent: the front originates one
        status, headers, _ = _get(front.port, "/x/originate")
        assert status == 200
        tp = a.seen_traceparent["/x/originate"]
        assert tp and tp.startswith("00-")
        # client DID send one: same trace id, front's own span id
        client_trace = "cd" * 16
        _get(front.port, "/x/join", headers={
            "traceparent": f"00-{client_trace}-{'ab' * 8}-01",
        })
        tp = a.seen_traceparent["/x/join"]
        assert tp is not None and tp.split("-")[1] == client_trace
        assert tp.split("-")[2] != "ab" * 8  # the front's hop, not the client's
        # the front's own ring now holds the joined front.route tree.
        # Bounded wait: the route span finishes in the handler's finally
        # AFTER the response bytes drain, so a client can legitimately
        # read the full response a tick before the span lands in the ring
        deadline = time.time() + 5
        while True:
            spans = [
                s for s in get_tracer().snapshot()
                if s.trace_id == client_trace
            ]
            if {s.name for s in spans} >= {"front.route", "front.proxy"}:
                break
            assert time.time() < deadline, {s.name for s in spans}
            time.sleep(0.02)
        # /fleet/traces stitches the stub's foreign spans + the front's
        status, _, body = _get(front.port, "/fleet/traces")
        doc = json.loads(body)
        assert doc["enabled"] is True
        ids = {t["trace_id"] for t in doc["traces"]}
        assert client_trace in ids and "ab" * 16 in ids
        # chrome export is lane-per-process
        status, _, body = _get(front.port, "/fleet/traces?format=chrome")
        chrome = json.loads(body)
        lanes = {
            e["args"]["name"] for e in chrome["traceEvents"]
            if e.get("name") == "process_name"
        }
        assert lanes == {"front", "r0"}
    finally:
        front.close()
        a.close()
        get_tracer().configure(enabled=False)


def test_untraced_front_forwards_client_traceparent_verbatim(tmp_path):
    a = _StubReplica("r0")
    front = _front_for(tmp_path, [a])  # tracing off (default)
    try:
        tp = f"00-{'ee' * 16}-{'ff' * 8}-01"
        _get(front.port, "/x/passthrough", headers={"traceparent": tp})
        assert a.seen_traceparent["/x/passthrough"] == tp
    finally:
        front.close()
        a.close()


# ---- real two-replica e2e (the acceptance criterion) -----------------------


def _model_message(gen: int) -> str:
    import numpy as np

    from oryx_tpu.common.artifact import ModelArtifact

    rng = np.random.default_rng(gen)
    n_users, n_items, f = 32, 64, 4
    art = ModelArtifact(
        "als",
        extensions={
            "features": str(f), "lambda": "0.001", "alpha": "1.0",
            "implicit": "true", "logStrength": "false",
        },
        tensors={
            "X": rng.standard_normal((n_users, f), dtype=np.float32),
            "Y": rng.standard_normal((n_items, f), dtype=np.float32),
        },
    )
    art.set_extension("XIDs", [f"u{j}" for j in range(n_users)])
    art.set_extension("YIDs", [f"i{j}" for j in range(n_items)])
    return art.to_string()


def test_two_replica_front_yields_one_stitched_trace_and_exemplars(tmp_path):
    """ISSUE 14 acceptance: a traced request through a 2-replica front
    yields ONE stitched trace on /fleet/traces containing front-hop and
    replica-side (request + batcher device) spans under the same trace
    id, Perfetto-loadable under ?format=chrome; and the traced request's
    trace id appears in the same latency bucket's exemplar on the
    federated OpenMetrics page as on the replica's own /metrics."""
    parser = pytest.importorskip("prometheus_client.openmetrics.parser")
    from oryx_tpu.bus.broker import get_broker, topics
    from oryx_tpu.common.executil import (
        config_overlay_from_sets, cpu_subprocess_env, free_port_run,
    )
    from oryx_tpu.common.freshness import publish_stamp
    from oryx_tpu.fleet import FleetSupervisor

    bus = f"file://{tmp_path / 'bus'}"
    topics.maybe_create(bus, "OryxInput", 1)
    topics.maybe_create(bus, "OryxUpdate", 1)
    broker = get_broker(bus)
    broker.send("OryxUpdate", "MODEL", _model_message(1))
    broker.send("OryxUpdate", "TRACE", publish_stamp(generation=1))

    base_port = free_port_run(2)
    sets = [
        "oryx.id=obs-e2e",
        f"oryx.input-topic.broker={bus}",
        f"oryx.update-topic.broker={bus}",
        "oryx.serving.model-manager-class="
        "oryx_tpu.apps.als.serving.ALSServingModelManager",
        'oryx.serving.application-resources='
        '["oryx_tpu.serving.resources.common",'
        '"oryx_tpu.serving.resources.als"]',
        "oryx.serving.api.read-only=true",
        "oryx.serving.api.loops=1",
        "oryx.fleet.replicas=2",
        f"oryx.fleet.base-port={base_port}",
        f"oryx.fleet.data-dir={tmp_path / 'fleet'}",
        "oryx.fleet.front.probe-interval-sec=0.3",
        # the whole fleet traces: children AND the front
        "oryx.monitoring.tracing.enabled=true",
        f"oryx.monitoring.flight.dir={tmp_path / 'front-flight'}",
    ]
    cfg = load_config(overlay=config_overlay_from_sets(sets))
    argv = [x for s in sets for x in ("--set", s)]
    sup = FleetSupervisor(
        cfg, argv=argv, env=cpu_subprocess_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    front = None
    try:
        sup.start()
        sup.wait_listening(90)
        for _, host, port in sup.backends():
            deadline = time.time() + 60
            while True:
                c = http.client.HTTPConnection(host, port, timeout=5)
                c.request("GET", "/ready")
                r = c.getresponse()
                r.read()
                c.close()
                if r.status == 200:
                    break
                assert time.time() < deadline, f"replica :{port} never ready"
                time.sleep(0.3)
        front = FleetFront(cfg, backends=sup.backends(), port=0)
        front.start()

        trace_id = os.urandom(16).hex()
        status, headers, body = _get(
            front.port, "/recommend/u1?howMany=3",
            headers={"traceparent": f"00-{trace_id}-{'12' * 8}-01"},
        )
        assert status == 200, (status, body)
        # the replica's response traceparent rode through the front and
        # stayed in OUR trace
        assert headers.get("traceparent", "").split("-")[1] == trace_id

        # ONE stitched trace holding front-hop AND replica-side spans
        stitched = None
        deadline = time.time() + 20
        while time.time() < deadline:
            status, _, body = _get(front.port, "/fleet/traces")
            doc = json.loads(body)
            match = [t for t in doc["traces"] if t["trace_id"] == trace_id]
            if match:
                names = {s["name"] for s in match[0]["spans"]}
                if {"front.route", "http.request", "batcher.device"} <= names:
                    stitched = match[0]
                    break
            time.sleep(0.3)
        assert stitched is not None, "stitched trace never materialized"
        assert "front" in stitched["processes"]
        replica_procs = [p for p in stitched["processes"] if p != "front"]
        assert len(replica_procs) == 1  # one replica answered
        rid = replica_procs[0]
        by_proc = {}
        for s in stitched["spans"]:
            by_proc.setdefault(s["process"], set()).add(s["name"])
        assert {"front.route", "front.proxy"} <= by_proc["front"]
        assert {"http.request", "http.dispatch", "batcher.device"} <= by_proc[rid]

        # Perfetto-loadable chrome export: our trace's events span 2 lanes
        status, _, body = _get(front.port, "/fleet/traces?format=chrome")
        chrome = json.loads(body)
        pids = {
            e["pid"] for e in chrome["traceEvents"]
            if e.get("ph") == "X" and e["args"].get("trace_id") == trace_id
        }
        assert len(pids) == 2, "front and replica must be separate lanes"

        # exemplar fidelity through federation (OpenMetrics negotiation)
        om = {"Accept": "application/openmetrics-text"}
        ports = dict(
            (replica_id, port) for replica_id, _, port in sup.backends()
        )

        def _exemplars(text, want_replica_label):
            fams = {
                f.name: f
                for f in parser.text_string_to_metric_families(text)
            }
            out = {}
            fam = fams.get("oryx_serving_request_seconds")
            for s in (fam.samples if fam else ()):
                if s.exemplar and s.exemplar.labels.get("trace_id") == trace_id:
                    if want_replica_label:
                        assert s.labels.get("replica") == rid
                    out[s.labels["le"]] = s.exemplar.labels["trace_id"]
            return out

        c = http.client.HTTPConnection("127.0.0.1", ports[rid], timeout=10)
        c.request("GET", "/metrics", headers=om)
        own_page = c.getresponse().read().decode()
        c.close()
        own = _exemplars(own_page, want_replica_label=False)
        assert own, "replica's own /metrics lost the traced exemplar"

        status, headers, body = _get(front.port, "/fleet/metrics", headers=om)
        assert headers["Content-Type"].startswith("application/openmetrics-text")
        fed_page = body.decode()
        fed = _exemplars(fed_page, want_replica_label=True)
        assert fed == own, (
            "the traced request's trace id must ride the SAME latency "
            f"bucket's exemplar through federation (own={own}, fed={fed})"
        )
    finally:
        if front is not None:
            front.close()
        sup.stop()
        get_tracer().configure(enabled=False)
