"""Checker registry: every shipped checker, in report order."""

from tools.oryxlint.checkers.consistency import ConsistencyChecker
from tools.oryxlint.checkers.eventloop import EventLoopChecker
from tools.oryxlint.checkers.jaxpurity import JaxPurityChecker
from tools.oryxlint.checkers.lockdiscipline import LockDisciplineChecker

ALL_CHECKERS = [
    EventLoopChecker,
    LockDisciplineChecker,
    JaxPurityChecker,
    ConsistencyChecker,
]
